"""async_take: consistency point, commit protocol, fault injection
(reference: tests/test_async_take.py — SlowFS/FaultyFS plugin subclassing,
error propagation through wait(), metadata-not-committed assertions)."""

import asyncio
import os
import time

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.io_types import WriteIO
from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.test_utils import run_with_subprocesses


# The commit fence (.snapshot_fence) is a control file written
# synchronously at plan time — BEFORE async_take returns, which is what
# makes the fenced GC sound (see snapshot._take_impl). Slow/faulty
# payload-write plugins must exempt it: these tests target the PAYLOAD
# write path (staged in the background), not the fence plant.
def _is_payload(write_io: WriteIO) -> bool:
    return not (
        write_io.path == SNAPSHOT_METADATA_FNAME
        or write_io.path.endswith(".snapshot_fence")
    )


class SlowFSStoragePlugin(FSStoragePlugin):
    WRITE_DELAY_S = 1.0

    async def write(self, write_io: WriteIO) -> None:
        if _is_payload(write_io):
            await asyncio.sleep(self.WRITE_DELAY_S)
        await super().write(write_io)


class FaultyFSStoragePlugin(FSStoragePlugin):
    async def write(self, write_io: WriteIO) -> None:
        if _is_payload(write_io):
            raise RuntimeError("injected storage failure")
        await super().write(write_io)


def test_async_take_completes(tmp_path, monkeypatch) -> None:
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        SlowFSStoragePlugin,
    )
    app_state = {"m": StateDict(w=np.arange(1000, dtype=np.float32))}
    t0 = time.monotonic()
    pending = Snapshot.async_take(str(tmp_path / "snap"), app_state)
    returned_after = time.monotonic() - t0
    snapshot = pending.wait()
    assert pending.done()
    # The slow write must not have blocked the caller. Cold-start overhead
    # (first event loop, thread pools) can cost a few hundred ms on its own,
    # so the bound is a margin below the write delay, not near-zero.
    assert returned_after < SlowFSStoragePlugin.WRITE_DELAY_S * 0.9
    dst = StateDict(w=np.zeros(1000, dtype=np.float32))
    snapshot.restore({"m": dst})
    np.testing.assert_array_equal(dst["w"], app_state["m"]["w"])


def test_async_take_consistency_point(tmp_path, monkeypatch) -> None:
    """Mutations after async_take returns must not affect the snapshot —
    staging completes before return (reference: snapshot.py:257-262)."""
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        SlowFSStoragePlugin,
    )
    arr = np.arange(256, dtype=np.float64)
    app_state = {"m": StateDict(w=arr, step=1)}
    pending = Snapshot.async_take(str(tmp_path / "snap"), app_state)
    arr[:] = -1.0  # mutate while storage I/O is still in flight
    snapshot = pending.wait()
    dst = StateDict(w=np.zeros(256, dtype=np.float64), step=0)
    snapshot.restore({"m": dst})
    np.testing.assert_array_equal(dst["w"], np.arange(256, dtype=np.float64))


def test_async_take_error_propagation(tmp_path, monkeypatch) -> None:
    """Failures surface through wait() AND the metadata is never committed
    (reference: tests/test_async_take.py:53-64)."""
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        FaultyFSStoragePlugin,
    )
    app_state = {"m": StateDict(w=np.ones(64, dtype=np.float32))}
    pending = Snapshot.async_take(str(tmp_path / "snap"), app_state)
    with pytest.raises(RuntimeError, match="injected storage failure"):
        pending.wait()
    assert pending.done()
    assert not (tmp_path / "snap" / SNAPSHOT_METADATA_FNAME).exists()


def test_sync_take_error_no_commit(tmp_path, monkeypatch) -> None:
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
        FaultyFSStoragePlugin,
    )
    with pytest.raises(RuntimeError, match="injected storage failure"):
        Snapshot.take(
            str(tmp_path / "snap"),
            {"m": StateDict(w=np.ones(64, dtype=np.float32))},
        )
    assert not (tmp_path / "snap" / SNAPSHOT_METADATA_FNAME).exists()


def _async_take_worker(rank: int, world_size: int, snap_path: str):
    from torchsnapshot_tpu import Snapshot, StateDict

    app_state = {
        "model": StateDict(w=np.arange(100, dtype=np.float32)),
        "local": StateDict(step=rank),
    }
    pending = Snapshot.async_take(snap_path, app_state, replicated=["model/*"])
    snapshot = pending.wait()
    return sorted(snapshot.get_manifest().keys())


@pytest.mark.multiprocess
def test_async_take_multiprocess(tmp_path) -> None:
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(_async_take_worker, 2, snap_path)
    assert results[0] == results[1]
    assert os.path.exists(os.path.join(snap_path, SNAPSHOT_METADATA_FNAME))


class _Rank1FaultyPlugin(FSStoragePlugin):
    async def write(self, write_io) -> None:
        raise RuntimeError("rank-1 injected failure")


def _async_take_one_rank_fails_worker(rank: int, world_size: int, snap_path: str):
    import unittest.mock as mock

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME as MD

    app_state = {"local": StateDict(data=np.full(1000, rank, dtype=np.float32))}

    if rank == 1:
        ctx = mock.patch(
            "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
            _Rank1FaultyPlugin,
        )
    else:
        ctx = mock.patch(
            "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin",
            SlowFSStoragePlugin,
        )

    with ctx:
        pending = Snapshot.async_take(snap_path, app_state)
        try:
            pending.wait()
            return "committed"
        except RuntimeError as e:
            return f"error: {e}"


@pytest.mark.multiprocess
def test_async_take_all_or_nothing(tmp_path) -> None:
    """If any rank fails, no rank commits and everyone sees an error
    (reference: tests/test_async_take.py:107-115)."""
    snap_path = str(tmp_path / "snap")
    results = run_with_subprocesses(
        _async_take_one_rank_fails_worker, 2, snap_path
    )
    assert all(r.startswith("error") for r in results.values()), results
    assert not os.path.exists(os.path.join(snap_path, SNAPSHOT_METADATA_FNAME))


def test_warmup_staging_prefaults_exact_sizes(tmp_path):
    """warmup_staging must draw the same slab sizes the real staging pass
    will: a second warmup reports nothing left to fault, and an
    async_take after warmup recycles the warmed slabs instead of
    allocating fresh ones."""
    import gc

    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict, warmup_staging
    from torchsnapshot_tpu.io_preparers.array import _staging_pool

    state = {
        "app": StateDict(
            a=np.random.default_rng(0).standard_normal((1 << 18,)).astype(np.float32),
            b=np.arange(1 << 16, dtype=np.int64),
        )
    }
    nbytes = sum(x.nbytes for x in state["app"].values())
    warmed = warmup_staging(state)
    assert warmed >= nbytes  # everything faulted up front
    assert warmup_staging(state) == 0  # already pooled: nothing to do

    before = {
        n: [s.ctypes.data for s in slabs] for n, slabs in _staging_pool._free.items()
    }
    Snapshot.async_take(str(tmp_path / "s"), state).wait()
    gc.collect()
    # The staged buffers came from (and returned to) the warmed slabs.
    after = {
        n: [s.ctypes.data for s in slabs] for n, slabs in _staging_pool._free.items()
    }
    for size, ptrs in before.items():
        assert set(ptrs) <= set(after.get(size, [])), size
    assert warmup_staging(state) == 0


def test_warmup_staging_sharded_piece_sizes():
    """For a GSPMD-sharded array, warmup sizes the pool from the owned
    write pieces, not the full array."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    from torchsnapshot_tpu import StateDict, warmup_staging
    from torchsnapshot_tpu.io_preparers.sharded import ShardedArrayIOPreparer

    devs = jax.devices()
    if len(devs) < 2:
        import pytest

        pytest.skip("needs multiple devices")
    mesh = Mesh(np.array(devs), ("x",))
    arr = jax.device_put(
        jnp.arange(8 * len(devs) * 128, dtype=jnp.float32).reshape(
            8 * len(devs), 128
        ),
        NamedSharding(mesh, PartitionSpec("x", None)),
    )
    piece_sizes = ShardedArrayIOPreparer.staged_piece_sizes(arr)
    assert sum(piece_sizes) == arr.nbytes  # single process owns every piece
    assert len(piece_sizes) == len(devs)
    warmed = warmup_staging({"app": StateDict(w=arr)})
    assert warmed >= sum(piece_sizes)
