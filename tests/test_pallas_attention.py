"""Pallas flash-attention kernel correctness (interpret mode on CPU).

Oracle: dense attention — same pattern as the ring/Ulysses tests. On CPU
the kernel runs in Pallas interpret mode; on TPU the identical code
compiles to a Mosaic kernel.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from torchsnapshot_tpu.ops import dense_attention, flash_attention

B, S, H, D = 2, 64, 2, 16


def make_qkv(seed: int = 0, dtype=jnp.float32):
    ks = jax.random.split(jax.random.PRNGKey(seed), 3)
    return tuple(jax.random.normal(k, (B, S, H, D), dtype) for k in ks)


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("block", [16, 32, 64])
def test_flash_matches_dense(causal: bool, block: int) -> None:
    q, k, v = make_qkv()
    ref = dense_attention(q, k, v, causal=causal)
    out = flash_attention(q, k, v, causal=causal, block_q=block, block_k=block)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_uneven_blocks() -> None:
    q, k, v = make_qkv(seed=1)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=32)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_bf16() -> None:
    q, k, v = make_qkv(seed=2, dtype=jnp.bfloat16)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True, block_q=16, block_k=16)
    np.testing.assert_allclose(
        np.asarray(out, np.float32), np.asarray(ref, np.float32), atol=3e-2
    )


@pytest.mark.parametrize("causal", [True, False])
@pytest.mark.parametrize("blocks", [(16, 16), (16, 32), (32, 16)])
def test_flash_gradients_match_dense(causal: bool, blocks) -> None:
    """Backward runs through the Pallas dq / dkv kernels (not recompute)."""
    bq, bk = blocks
    q, k, v = make_qkv(seed=3)

    def loss_f(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, causal=causal, block_q=bq, block_k=bk) ** 2
        )

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=causal) ** 2)

    g_f = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_f, g_d):
        np.testing.assert_allclose(np.asarray(gf), np.asarray(gd), atol=1e-4)


def test_flash_gradients_bf16() -> None:
    q, k, v = make_qkv(seed=6, dtype=jnp.bfloat16)

    def loss_f(q, k, v):
        return jnp.sum(
            flash_attention(q, k, v, block_q=16, block_k=16).astype(jnp.float32) ** 2
        )

    def loss_d(q, k, v):
        return jnp.sum(dense_attention(q, k, v, causal=True).astype(jnp.float32) ** 2)

    g_f = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
    g_d = jax.grad(loss_d, argnums=(0, 1, 2))(q, k, v)
    for gf, gd in zip(g_f, g_d):
        np.testing.assert_allclose(
            np.asarray(gf, np.float32), np.asarray(gd, np.float32), atol=0.1
        )


def test_flash_indivisible_raises() -> None:
    q, k, v = make_qkv(seed=4)
    with pytest.raises(ValueError, match="divisible"):
        flash_attention(q, k, v, block_q=48, block_k=48)


def test_flash_default_blocks_snap_to_divisor() -> None:
    """Default blocks auto-pick the largest divisor of S (<= 512): a seq
    len like 160 (divisible by 32, not by 512) must run, not raise."""
    ks = jax.random.split(jax.random.PRNGKey(11), 3)
    q, k, v = (jax.random.normal(kk, (1, 160, 2, 16)) for kk in ks)
    ref = dense_attention(q, k, v, causal=True)
    out = flash_attention(q, k, v, causal=True)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


@pytest.mark.slow
def test_transformer_flash_matches_dense() -> None:
    from torchsnapshot_tpu.models import transformer as T

    base = dict(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=S, dtype=jnp.float32,
    )
    params = T.init_params(jax.random.PRNGKey(0), T.TransformerConfig(**base))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (2, S), 0, 128)
    ref = T.forward(params, tokens, T.TransformerConfig(**base))
    out = T.forward(
        params, tokens,
        T.TransformerConfig(**base, attn_impl="flash", attn_block_size=16),
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_flash_sharded_matches_dense() -> None:
    """shard_mapped kernel over a ('data','model') mesh == dense oracle."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.ops.pallas_attention import flash_attention_sharded

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "model"))
    q, k, v = make_qkv(seed=7)
    ref = dense_attention(q, k, v, causal=True)
    qs, ks_, vs = (
        jax.device_put(t, NamedSharding(mesh, P("data", None, "model", None)))
        for t in (q, k, v)
    )
    out = jax.jit(
        lambda q, k, v: flash_attention_sharded(q, k, v, mesh, causal=True)
    )(qs, ks_, vs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_flash_sharded_head_indivisible_raises() -> None:
    from jax.sharding import Mesh

    from torchsnapshot_tpu.ops.pallas_attention import flash_attention_sharded

    mesh = Mesh(np.array(jax.devices()[:3]).reshape(1, 3), ("data", "model"))
    q, k, v = make_qkv(seed=8)  # H=2, not divisible by 3
    with pytest.raises(ValueError, match="divisible"):
        flash_attention_sharded(q, k, v, mesh)


def test_transformer_flash_with_mesh_matches_dense() -> None:
    """attn_impl='flash' under a tp mesh routes through the shard_mapped
    kernel and matches the meshless dense forward."""
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.models import transformer as T

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("data", "model"))
    base = dict(
        vocab_size=128, d_model=32, n_heads=4, n_layers=2, d_ff=64,
        max_seq_len=S, dtype=jnp.float32,
    )
    params = T.init_params(jax.random.PRNGKey(0), T.TransformerConfig(**base))
    tokens = jax.random.randint(jax.random.PRNGKey(1), (4, S), 0, 128)
    ref = T.forward(params, tokens, T.TransformerConfig(**base, attn_impl="dense"))
    st = jax.device_put(tokens, NamedSharding(mesh, P("data", None)))
    out = jax.jit(
        lambda p, t: T.forward(
            p, t,
            T.TransformerConfig(**base, attn_impl="flash", attn_block_size=16),
            mesh=mesh,
        )
    )(params, st)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=2e-4)


def test_ulysses_flash_inner() -> None:
    from jax.sharding import Mesh

    from torchsnapshot_tpu.ops import ulysses_attention_sharded

    mesh = Mesh(np.array(jax.devices()[:2]).reshape(2), ("seq",))
    q, k, v = make_qkv(seed=5)
    ref = dense_attention(q, k, v, causal=True)
    out = ulysses_attention_sharded(
        q, k, v, mesh, causal=True, inner="flash", inner_block_size=16
    )
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)
