"""Seeded chaos matrix: deterministic fault schedules against real takes
and restores, asserting the library's core invariant on every one:

    every faulted run either commits a bit-exact restorable snapshot, or
    leaves the previous snapshot restorable and the directory fsck-clean
    — and a commit that is NOT bit-exact restorable must be fsck-dirty
    (detectable), never silently wrong.

The matrix spans the fs, s3-emulated (FakeS3Client), and mirrored
backends at world size 1 in-process, world size 2 via the subprocess
launcher, SIGKILL schedules in real subprocesses, and the bounded
barrier-deadline drill (TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT) for rank
death mid-plan. Schedules are plain fault-plan strings — replay any of
them outside the suite with TORCHSNAPSHOT_TPU_FAULT_PLAN=<plan>.

A slow randomized soak over the same invariant lives in
benchmarks/chaos_soak.py.
"""

from __future__ import annotations

import os
import signal
import subprocess
import sys

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict, faultinject
from torchsnapshot_tpu.cli import run_fsck
from torchsnapshot_tpu.manifest import CorruptSnapshotError
from torchsnapshot_tpu.storage_plugins.retry import CollectiveRetryStrategy


def _state(seed: int, big: bool = False) -> dict:
    rng = np.random.default_rng(seed)
    leaves = {
        "w": rng.standard_normal(20_000).astype(np.float32),
        "b": rng.standard_normal(3_000).astype(np.float64),
        "step": np.array([seed], dtype=np.int64),
    }
    if big:
        # Large enough for the streaming write election (sub-chunk
        # pwrites), so fs.pwrite schedules hit a live site.
        leaves["big"] = rng.standard_normal(3_000_000).astype(np.float32)
    return {"model": StateDict(**leaves)}


def _zeros_like(state: dict) -> dict:
    return {
        "model": StateDict(
            **{
                k: np.zeros_like(np.asarray(v))
                for k, v in state["model"].items()
            }
        )
    }


def _equal(a: dict, b: dict) -> bool:
    return all(
        np.array_equal(np.asarray(a["model"][k]), np.asarray(b["model"][k]))
        for k in a["model"]
    )


def _committed(path: str, opts) -> bool:
    try:
        Snapshot(path, storage_options=opts).metadata
        return True
    except Exception:  # noqa: BLE001 - missing, corrupt, backend-specific
        return False


async def _nosleep(_s: float) -> None:
    return None


def _backend(kind: str, tmp_path):
    """(prev_path, cur_path, storage_options, fsck_opts, local_cur)."""
    if kind == "fs":
        return (
            str(tmp_path / "prev"),
            str(tmp_path / "cur"),
            None,
            None,
            str(tmp_path / "cur"),
        )
    if kind == "s3":
        from tests.test_s3_storage_plugin import FakeS3Client

        opts = {
            "client": FakeS3Client(),
            "retry_strategy": CollectiveRetryStrategy(
                stall_timeout_s=0.5, sleep=_nosleep
            ),
        }
        return ("s3://bucket/prev", "s3://bucket/cur", opts, opts, None)
    if kind == "mirror":
        def opts_for(name):
            return {"mirror_url": str(tmp_path / f"mirror_{name}")}

        return (
            str(tmp_path / "prev"),
            str(tmp_path / "cur"),
            opts_for("cur"),
            None,
            str(tmp_path / "cur"),
        )
    raise AssertionError(kind)


def _check_take_invariant(
    backend, tmp_path, plan: str, big: bool = False
) -> str:
    """Run one take-phase schedule; assert the binary invariant."""
    state0, state1 = _state(0, big), _state(1, big)
    prev, cur, opts, fsck_opts, local_cur = _backend(backend, tmp_path)
    prev_opts = (
        {"mirror_url": str(tmp_path / "mirror_prev")}
        if backend == "mirror"
        else opts
    )
    Snapshot.take(prev, state0, storage_options=prev_opts)

    faultinject.configure(plan)
    err = None
    try:
        Snapshot.take(cur, state1, storage_options=opts)
    except BaseException as e:  # noqa: B036
        err = e
    finally:
        faultinject.disable()

    if _committed(cur, fsck_opts):
        dst = _zeros_like(state1)
        exact = False
        try:
            Snapshot(cur, storage_options=fsck_opts).restore(dst)
            exact = _equal(dst, state1)
        except Exception:  # noqa: BLE001
            exact = False
        if not exact:
            # Committed-but-not-restorable is tolerable ONLY when fsck
            # can see it — silent corruption is the bug class this
            # matrix exists to catch.
            code, report = run_fsck(cur, storage_options=fsck_opts)
            assert code != 0, (
                f"plan {plan!r}: committed, not bit-exact restorable, and "
                f"fsck reports clean — silent corruption"
            )
            return "committed-detectable"
        return "committed"

    # Not committed: the previous snapshot must be untouched and the
    # rubble must read as a partial/corrupt commit, never as a valid
    # snapshot. Normally the take also surfaced a failure; the one
    # exception is storage silently corrupting the metadata bytes at the
    # commit point (corrupt/truncate plans on commit.metadata), where the
    # writer cannot know — there, detection is fsck's job.
    if err is None:
        assert local_cur is not None, (
            f"plan {plan!r}: no commit and no error on a backend fsck "
            "cannot scan"
        )
        code, _ = run_fsck(local_cur, storage_options=fsck_opts)
        assert code == 1, (
            f"plan {plan!r}: take reported success, nothing committed, and "
            f"fsck exits {code} — a silent non-commit"
        )
    dst0 = _zeros_like(state0)
    Snapshot(prev, storage_options=prev_opts).restore(dst0)
    assert _equal(dst0, state0), f"plan {plan!r}: previous snapshot damaged"
    code, _ = run_fsck(prev, storage_options=prev_opts)
    assert code == 0, f"plan {plan!r}: previous snapshot not fsck-clean"
    if local_cur is not None and os.path.isdir(local_cur):
        code, _ = run_fsck(local_cur, storage_options=fsck_opts)
        assert code in (1, 2), f"plan {plan!r}: rubble fsck'd clean"
    return "aborted"


def _check_restore_invariant(backend, tmp_path, plan: str, big: bool = False) -> str:
    """Run one restore-phase schedule: a faulted restore must either
    deliver bit-exact data or raise — never return silently-wrong bytes
    — and a clean retry afterwards must succeed bit-exact."""
    state1 = _state(1, big)
    _prev, cur, opts, fsck_opts, _local = _backend(backend, tmp_path)
    Snapshot.take(cur, state1, storage_options=opts)

    faultinject.configure(plan)
    dst = _zeros_like(state1)
    err = None
    try:
        Snapshot(cur, storage_options=opts).restore(dst)
    except Exception as e:  # noqa: BLE001
        err = e
    finally:
        faultinject.disable()
    if err is None:
        assert _equal(dst, state1), (
            f"plan {plan!r}: restore returned silently-wrong data"
        )
        outcome = "restored"
    else:
        outcome = "raised"

    dst2 = _zeros_like(state1)
    Snapshot(cur, storage_options=opts).restore(dst2)
    assert _equal(dst2, state1), f"plan {plan!r}: clean retry not bit-exact"
    code, _ = run_fsck(cur, storage_options=fsck_opts)
    assert code == 0, f"plan {plan!r}: snapshot dirtied by a faulted restore"
    return outcome


# --------------------------------------------------------- world size 1

FS_TAKE_PLANS = [
    "fs.write@1=transient",                 # the fence write itself
    "fs.write@2=transient",                 # first payload
    "fs.write@2=permanent",
    "fs.write@3=permanent",
    "scheduler.stage@1=permanent",
    "scheduler.stage@2=transient",
    "commit.metadata@1=corrupt;seed=11",    # torn commit point
    "commit.metadata@1=truncate:0.3",
    "fs.write@2=corrupt;seed=12",           # silent write corruption
    "fs.write@3=truncate:0.5",
    "fs.write@p0.4=transient;seed=1",
    "fs.write@p0.4=transient;seed=2",
    "fs.write@p0.2=permanent;seed=3",
    "fs.write@50=transient",                # past the write window: no-op
    "fs.write@2=delay:0.02;fs.write@3=delay:0.02",
]


@pytest.mark.parametrize("plan", FS_TAKE_PLANS)
def test_chaos_fs_take(tmp_path, plan):
    outcome = _check_take_invariant("fs", tmp_path, plan)
    if plan in ("fs.write@50=transient",
                "fs.write@2=delay:0.02;fs.write@3=delay:0.02"):
        assert outcome == "committed"
    if plan.startswith("scheduler.stage@1") or plan.startswith("fs.write@1="):
        assert outcome == "aborted"


def test_chaos_fs_take_streamed_pwrite(tmp_path):
    outcome = _check_take_invariant(
        "fs", tmp_path, "fs.pwrite@2=transient", big=True
    )
    assert outcome in ("aborted", "committed")


FS_RESTORE_PLANS = [
    "fs.read@1=permanent",
    "fs.read@2=transient",
    "fs.read@1=corrupt;seed=5",
    "fs.read@1=truncate:0.5",
    "fs.read@p0.5=transient;seed=6",
    "fs.read@2=delay:0.02",
]


@pytest.mark.parametrize("plan", FS_RESTORE_PLANS)
def test_chaos_fs_restore(tmp_path, plan):
    outcome = _check_restore_invariant("fs", tmp_path, plan)
    if plan == "fs.read@2=delay:0.02":
        assert outcome == "restored"
    if plan in ("fs.read@1=permanent", "fs.read@1=corrupt;seed=5"):
        assert outcome == "raised"


S3_TAKE_PLANS = [
    "s3.put@1=transient",            # absorbed by the collective retry
    "s3.put@p0.5=transient;seed=4",  # every attempt eventually lands
    "s3.put@1+=transient",           # service down: fleet gives up
    "s3.put@2=permanent",
    "s3.put@2=corrupt;seed=6",       # corrupt stored object
    "s3.put@2=truncate:0.5",
]


@pytest.mark.parametrize("plan", S3_TAKE_PLANS)
def test_chaos_s3_take(tmp_path, plan):
    outcome = _check_take_invariant("s3", tmp_path, plan)
    if plan in ("s3.put@1=transient", "s3.put@p0.5=transient;seed=4"):
        # Transient blips must be absorbed by retry, not abort the take.
        assert outcome == "committed"
    if plan == "s3.put@1+=transient":
        assert outcome == "aborted"


S3_RESTORE_PLANS = [
    "s3.get@1=transient",       # retried
    "s3.get@1+=permanent",      # service down
    "s3.get@2=corrupt;seed=9",  # checksum catches it
]


@pytest.mark.parametrize("plan", S3_RESTORE_PLANS)
def test_chaos_s3_restore(tmp_path, plan):
    outcome = _check_restore_invariant("s3", tmp_path, plan)
    if plan == "s3.get@1=transient":
        assert outcome == "restored"


MIRROR_TAKE_PLANS = [
    "fs.write@3=transient",   # may hit either tier; binary outcome holds
    "fs.write@4=permanent",
    "fs.write@p0.3=transient;seed=8",
]


@pytest.mark.parametrize("plan", MIRROR_TAKE_PLANS)
def test_chaos_mirror_take(tmp_path, plan):
    _check_take_invariant("mirror", tmp_path, plan)
    # Two-tier commit order: a committed mirror implies a committed
    # primary (mirror metadata is deferred until payload replication
    # drained) — never the other way around.
    mirror_meta = tmp_path / "mirror_cur" / ".snapshot_metadata"
    if mirror_meta.exists():
        assert (tmp_path / "cur" / ".snapshot_metadata").exists()


MIRROR_RESTORE_PLANS = [
    "mirror.primary_read@1=permanent",    # one read fails over
    "mirror.primary_read@1+=permanent",   # total primary loss
    "mirror.primary_read@2=transient",
]


@pytest.mark.parametrize("plan", MIRROR_RESTORE_PLANS)
def test_chaos_mirror_restore(tmp_path, plan):
    outcome = _check_restore_invariant("mirror", tmp_path, plan)
    # Failover is transparent: the mirror serves the bytes, bit-exact.
    assert outcome == "restored"


def test_chaos_mirror_total_primary_loss_restores_from_mirror(tmp_path):
    """Not a plan-string schedule but the same invariant: wipe the whole
    primary payload tree after commit; the mirror serves the restore."""
    import shutil

    state1 = _state(1)
    opts = {"mirror_url": str(tmp_path / "mirror_cur")}
    cur = str(tmp_path / "cur")
    Snapshot.take(cur, state1, storage_options=opts)
    shutil.rmtree(tmp_path / "cur" / "0")
    dst = _zeros_like(state1)
    Snapshot(cur, storage_options=opts).restore(dst)
    assert _equal(dst, state1)


# ------------------------------------------------------ SIGKILL schedules

KILL_PLANS = [
    "fs.write@2=kill",         # mid first payload
    "fs.write@4=kill",         # later in the write window
    "commit.metadata@1=kill",  # exactly at the commit point
]

_KILL_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict, faultinject

root, plan = sys.argv[1], sys.argv[2]

def state(seed):
    rng = np.random.default_rng(seed)
    return {"model": StateDict(
        w=rng.standard_normal(20_000).astype(np.float32),
        b=rng.standard_normal(3_000).astype(np.float64),
        step=np.array([seed], dtype=np.int64),
    )}

Snapshot.take(os.path.join(root, "prev"), state(0))
faultinject.configure(plan)
Snapshot.take(os.path.join(root, "cur"), state(1))
print("SURVIVED")  # only reachable if the plan never fired
"""


@pytest.mark.parametrize("plan", KILL_PLANS)
def test_chaos_sigkill(tmp_path, plan):
    r = subprocess.run(
        [sys.executable, "-c", _KILL_CHILD, str(tmp_path), plan],
        capture_output=True,
        text=True,
        timeout=150,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    assert "SURVIVED" not in r.stdout
    cur = str(tmp_path / "cur")
    assert not os.path.exists(os.path.join(cur, ".snapshot_metadata"))
    # The previous snapshot is untouched and fsck-clean.
    state0 = _state(0)
    dst = _zeros_like(state0)
    Snapshot(str(tmp_path / "prev")).restore(dst)
    assert _equal(dst, state0)
    assert run_fsck(str(tmp_path / "prev"))[0] == 0
    # The rubble reads as a partial commit (or nothing at all).
    if os.path.isdir(cur):
        assert run_fsck(cur)[0] in (1, 2)


# ------------------------------------------- native-engine schedules
#
# The same binary invariant, drilled THROUGH the io_uring fast path
# (ISSUE 9): env forces the native election and pins a small sub-chunk
# so the big entry streams through the fs.native_* sites.

_NATIVE_ENV = {
    "TORCHSNAPSHOT_TPU_NATIVE_IO": "always",
    "TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES": str(256 << 10),
    "TORCHSNAPSHOT_TPU_STREAM_READS": "always",
}


def _native_engine_ready() -> bool:
    from torchsnapshot_tpu import native_io

    return native_io.engine_kind() == "uring"


NATIVE_TAKE_PLANS = [
    "fs.native_pwrite@2=transient",
    "fs.native_pwrite@1=permanent",
    "fs.native_pwrite@3=truncate:0.5",
    "fs.native_pwrite@2=corrupt;seed=21",
    "fs.native_pwrite@p0.4=transient;seed=22",
]


@pytest.mark.parametrize("plan", NATIVE_TAKE_PLANS)
def test_chaos_native_take(tmp_path, plan, monkeypatch):
    if not _native_engine_ready():
        pytest.skip("io_uring unavailable")
    for key, val in _NATIVE_ENV.items():
        monkeypatch.setenv(key, val)
    outcome = _check_take_invariant("fs", tmp_path, plan, big=True)
    if plan == "fs.native_pwrite@1=permanent":
        assert outcome == "aborted"
    assert outcome in ("aborted", "committed", "committed-detectable")


NATIVE_RESTORE_PLANS = [
    "fs.native_pread@1=corrupt;seed=23",
    "fs.native_pread@2=transient",
    "fs.native_pread@1=truncate:0.5",
    "fs.native_pread@2=delay:0.02",
]


@pytest.mark.parametrize("plan", NATIVE_RESTORE_PLANS)
def test_chaos_native_restore(tmp_path, plan, monkeypatch):
    if not _native_engine_ready():
        pytest.skip("io_uring unavailable")
    for key, val in _NATIVE_ENV.items():
        monkeypatch.setenv(key, val)
    outcome = _check_restore_invariant("fs", tmp_path, plan, big=True)
    if plan.startswith("fs.native_pread@1=corrupt"):
        # The receiver-side chained CRC catches the flipped byte before
        # anything commits to the destination.
        assert outcome == "raised"
    if plan == "fs.native_pread@2=delay:0.02":
        assert outcome == "restored"


_NATIVE_KILL_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ["TORCHSNAPSHOT_TPU_NATIVE_IO"] = "always"
os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"] = str(256 << 10)
import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict, faultinject

root, plan = sys.argv[1], sys.argv[2]

def state(seed):
    rng = np.random.default_rng(seed)
    return {"model": StateDict(
        w=rng.standard_normal(20_000).astype(np.float32),
        big=rng.standard_normal(3_000_000).astype(np.float32),
        step=np.array([seed], dtype=np.int64),
    )}

Snapshot.take(os.path.join(root, "prev"), state(0))
faultinject.configure(plan)
Snapshot.take(os.path.join(root, "cur"), state(1))
print("SURVIVED")  # only reachable if the plan never fired
"""


def test_chaos_native_sigkill_mid_queue(tmp_path):
    """SIGKILL while SQEs are queued in the native engine: the kernel
    dies with the process's ring — the temp file never reaches the final
    path, the previous snapshot stays restorable + fsck-clean."""
    if not _native_engine_ready():
        pytest.skip("io_uring unavailable")
    plan = "fs.native_pwrite@2=kill"
    r = subprocess.run(
        [sys.executable, "-c", _NATIVE_KILL_CHILD, str(tmp_path), plan],
        capture_output=True,
        text=True,
        timeout=150,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    assert "SURVIVED" not in r.stdout
    cur = str(tmp_path / "cur")
    assert not os.path.exists(os.path.join(cur, ".snapshot_metadata"))
    rng = np.random.default_rng(0)
    expected = {
        "model": StateDict(
            w=rng.standard_normal(20_000).astype(np.float32),
            big=rng.standard_normal(3_000_000).astype(np.float32),
            step=np.array([0], dtype=np.int64),
        )
    }
    dst = _zeros_like(expected)
    Snapshot(str(tmp_path / "prev")).restore(dst)
    assert _equal(dst, expected)
    assert run_fsck(str(tmp_path / "prev"))[0] == 0
    if os.path.isdir(cur):
        assert run_fsck(cur)[0] in (1, 2)


# --------------------------------------------------------- world size 2


def _w2_state(rank: int, seed: int) -> dict:
    rng = np.random.default_rng(1000 * rank + seed)
    return {
        "model": StateDict(
            w=rng.standard_normal(8_000).astype(np.float32),
            step=np.array([seed], dtype=np.int64),
        )
    }


def _w2_take_worker(rank: int, world_size: int, root: str, plan: str,
                    victim: int):
    from torchsnapshot_tpu import faultinject as fi

    state0, state1 = _w2_state(rank, 0), _w2_state(rank, 1)
    Snapshot.take(os.path.join(root, "prev"), state0)
    if rank == victim:
        fi.configure(plan)
    err = None
    try:
        Snapshot.take(os.path.join(root, "cur"), state1)
    except BaseException as e:  # noqa: B036
        err = repr(e)
    finally:
        fi.disable()
    prev_ok = False
    dst = _zeros_like(state0)
    Snapshot(os.path.join(root, "prev")).restore(dst)
    prev_ok = _equal(dst, state0)
    return {"err": err, "prev_ok": prev_ok}


W2_TAKE_PLANS = [
    ("scheduler.stage@1=permanent", 1),
    ("fs.write@2=transient", 0),
    ("fs.write@1=permanent", 1),  # rank 1's first payload write
    # Drain-phase desertion regression: the delay parks the write task
    # past the manifest gather, so the transient fires inside rank 0's
    # post-gather sync_complete — the phase whose failures used to desert
    # peers at the commit barrier until the 1800 s timeout (now
    # propagated through the wrapper error channel).
    ("fs.write@2=delay:0.3;fs.write@2=transient", 0),
]


@pytest.mark.parametrize("plan,victim", W2_TAKE_PLANS)
def test_chaos_w2_take_abort_is_collective(tmp_path, plan, victim):
    """One rank's fault aborts the take on EVERY rank, commits nothing,
    and leaves the previous snapshot restorable on every rank."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _w2_take_worker, 2, str(tmp_path), plan, victim, timeout=180.0
    )
    for rank, out in results.items():
        assert out["err"] is not None, (rank, plan)
        assert out["prev_ok"], (rank, plan)
    assert not os.path.exists(tmp_path / "cur" / ".snapshot_metadata")
    assert run_fsck(str(tmp_path / "prev"))[0] == 0


def _w2_restore_worker(rank: int, world_size: int, root: str, plan: str,
                       victim: int):
    from torchsnapshot_tpu import faultinject as fi

    state1 = _w2_state(rank, 1)
    Snapshot.take(os.path.join(root, "cur"), state1)
    if rank == victim:
        fi.configure(plan)
    err = None
    dst = _zeros_like(state1)
    try:
        Snapshot(os.path.join(root, "cur")).restore(dst)
    except Exception as e:  # noqa: BLE001
        err = repr(e)
    finally:
        fi.disable()
    silently_wrong = err is None and not _equal(dst, state1)
    dst2 = _zeros_like(state1)
    Snapshot(os.path.join(root, "cur")).restore(dst2)
    return {
        "err": err,
        "silently_wrong": silently_wrong,
        "retry_ok": _equal(dst2, state1),
    }


def test_chaos_w2_restore_fault_is_local_and_recoverable(tmp_path):
    """A rank's read fault during a collective restore fails THAT rank
    cleanly (no hang, no silent corruption) and a clean retry restores
    bit-exact everywhere."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    # Hit 2, not 1: hit 1 is the .snapshot_metadata read, which fails
    # BEFORE the restore's first collective — an asymmetric pre-collective
    # abort that deserts rank 0's gather (bounded only by the barrier
    # timeout). Payload reads (hit 2 on) fail inside the lockstep-
    # protected key loop, the contract this drill exercises.
    results = run_with_subprocesses(
        _w2_restore_worker, 2, str(tmp_path), "fs.read@2=permanent", 1,
        timeout=180.0,
    )
    for rank, out in results.items():
        assert not out["silently_wrong"], rank
        assert out["retry_ok"], rank
    assert results[1]["err"] is not None


def _w2_rpc_death_worker(rank: int, world_size: int, root: str):
    from torchsnapshot_tpu import faultinject as fi

    state1 = _w2_state(rank, 1)
    if rank == 1:
        # Kill the coordination plane under rank 1 mid-take: every store
        # round trip from hit 6 on fails (the take's collectives start
        # around there; earlier hits cover the launcher's own plumbing).
        fi.configure("dist_store.rpc@6+=transient")
    err = None
    try:
        Snapshot.take(os.path.join(root, "cur"), state1)
    except BaseException as e:  # noqa: B036
        err = repr(e)
    finally:
        fi.disable()
    return err


def test_chaos_w2_rank_death_mid_plan_fails_fast(tmp_path, monkeypatch):
    """The barrier-timeout satellite drill: with
    TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT set, a rank whose coordination
    plane dies mid-take fails EVERY rank within the configured bound —
    not the 1800 s default — and nothing commits."""
    import time as _time

    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT", "8")
    t0 = _time.monotonic()
    results = run_with_subprocesses(
        _w2_rpc_death_worker, 2, str(tmp_path), timeout=120.0
    )
    elapsed = _time.monotonic() - t0
    for rank, err in results.items():
        assert err is not None, rank
    assert not os.path.exists(tmp_path / "cur" / ".snapshot_metadata")
    # Well under the 1800 s default; generous margin over the 8 s bound
    # for process spawn + jax import.
    assert elapsed < 100, elapsed


# ------------------------------------------- store-host SIGKILL schedules
#
# The coordination-store leader runs in a DEDICATED host process (the
# deployment whose death is survivable) and its fault plan SIGKILLs it at
# the Nth client op it serves — deterministically mid-take. With one
# replica (hosted by rank 1) the take must complete committed-bit-exact
# through transparent client failover; with zero replicas the same
# schedule must fail every rank within the bounded barrier deadline.

STORE_KILL_PLAN = "dist_store.serve_op@14=kill;seed=601"


def _store_kill_worker(rank: int, world_size: int, root: str):
    import numpy as np

    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    state = {
        "model": StateDict(
            w=np.random.default_rng(100 + rank)
            .standard_normal(20_000)
            .astype(np.float32),
            step=np.array([rank], dtype=np.int64),
        )
    }
    Snapshot.take(os.path.join(root, "cur"), state)
    # Restore-verify inside the same world: the failed-over store also
    # carries the restore's lockstep collectives.
    dst = {
        "model": StateDict(
            w=np.zeros(20_000, np.float32), step=np.zeros(1, np.int64)
        )
    }
    Snapshot(os.path.join(root, "cur")).restore(dst)
    bit_exact = all(
        np.array_equal(np.asarray(dst["model"][k]), np.asarray(state["model"][k]))
        for k in state["model"]
    )
    return {
        "failovers": get_default_pg().store.failovers,
        "bit_exact": bit_exact,
    }


def test_chaos_store_host_kill_mid_take_fails_over_and_commits(tmp_path):
    """The headline drill: SIGKILL the store leader mid-take at w2 with
    1 replica — the take completes committed-bit-exact via failover and
    each rank counts exactly one store failover."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _store_kill_worker,
        2,
        str(tmp_path),
        timeout=180.0,
        store_replicas=1,
        store_lease_s=0.5,
        external_store=True,
        store_host_plan=STORE_KILL_PLAN,
    )
    assert set(results) == {0, 1}, results
    for rank, out in results.items():
        assert out["bit_exact"], (rank, out)
        assert out["failovers"] == 1, (rank, out)
    assert os.path.exists(tmp_path / "cur" / ".snapshot_metadata")
    assert run_fsck(str(tmp_path / "cur"))[0] == 0


def _store_kill_no_replica_worker(rank: int, world_size: int, root: str):
    import numpy as np

    from torchsnapshot_tpu.dist_store import StoreConnectionLostError

    state = {
        "model": StateDict(
            w=np.random.default_rng(100 + rank)
            .standard_normal(20_000)
            .astype(np.float32),
        )
    }
    import time as _time

    t0 = _time.monotonic()
    try:
        Snapshot.take(os.path.join(root, "cur"), state)
    except BaseException as e:  # noqa: B036
        chain, cur, seen = [], e, set()
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            chain.append(cur)
            cur = cur.__cause__ or cur.__context__
        named = any(isinstance(c, StoreConnectionLostError) for c in chain)
        return {"aborted": True, "named": named,
                "elapsed": _time.monotonic() - t0}
    return {"aborted": False, "named": False,
            "elapsed": _time.monotonic() - t0}


def test_chaos_store_host_kill_no_replicas_fails_bounded(tmp_path, monkeypatch):
    """The SAME schedule with 0 replicas: every rank fails within the
    bounded barrier deadline (naming the store), nothing commits."""
    import time as _time

    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT", "15")
    t0 = _time.monotonic()
    results = run_with_subprocesses(
        _store_kill_no_replica_worker,
        2,
        str(tmp_path),
        timeout=150.0,
        external_store=True,
        store_host_plan=STORE_KILL_PLAN,
    )
    elapsed = _time.monotonic() - t0
    for rank, out in results.items():
        assert out["aborted"], (rank, out)
        assert out["named"], (rank, out)
    assert not os.path.exists(tmp_path / "cur" / ".snapshot_metadata")
    # Well under the 1800 s default; generous margin over the 15 s bound
    # for process spawn + jax import.
    assert elapsed < 120, elapsed


# ------------------------------------------------- delta-journal schedules
#
# The ISSUE 14 RPO drills: the journal's crash consistency under the same
# binary invariant — a faulted journal either replays bit-exact to the
# last COMMITTED epoch or is rejected whole (base-snapshot fallback),
# never a partial splice; torn tails are truncated, never trusted.


def _w2_journal_kill_worker(rank: int, world_size: int, root: str):
    os.environ["TORCHSNAPSHOT_TPU_JOURNAL"] = "1"
    from torchsnapshot_tpu import CheckpointManager
    from torchsnapshot_tpu import faultinject as fi

    mgr = CheckpointManager(root, save_interval_steps=100)
    st = _w2_state(rank, 0)
    mgr.save(0, st)
    # Epoch 1 commits cleanly on both ranks.
    st["model"]["w"] = np.asarray(st["model"]["w"]) + 1.0
    st["model"]["step"] = np.array([1], dtype=np.int64)
    assert mgr.journal_step(1, st)
    # Epoch 2: SIGKILL fires mid-append (frame prefix already on disk —
    # a genuinely torn record) on BOTH ranks.
    st["model"]["w"] = np.asarray(st["model"]["w"]) + 1.0
    st["model"]["step"] = np.array([2], dtype=np.int64)
    fi.configure("journal.append@1=kill")
    mgr.journal_step(2, st)
    return "survived"  # unreachable


def _w2_journal_restore_worker(rank: int, world_size: int, root: str):
    from torchsnapshot_tpu import CheckpointManager

    expected = _w2_state(rank, 0)
    expected["model"]["w"] = np.asarray(expected["model"]["w"]) + 1.0
    expected["model"]["step"] = np.array([1], dtype=np.int64)
    dst = _zeros_like(expected)
    step = CheckpointManager(root, save_interval_steps=100).restore(dst)
    return {"step": step, "bit_exact": _equal(dst, expected)}


def test_chaos_w2_journal_sigkill_mid_append(tmp_path):
    """The headline RPO drill: both ranks of a w2 world are SIGKILLed
    mid-append of journal epoch 2. A second world restores base + replay
    bit-exact to the last COMMITTED epoch (1), the torn epoch-2 tails are
    truncated, and the snapshot fscks clean after the stale epoch fence
    is repaired."""
    from torchsnapshot_tpu import journal
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    run_with_subprocesses(
        _w2_journal_kill_worker, 2, str(tmp_path),
        timeout=180.0, expect_dead=(0, 1),
    )
    snap = str(tmp_path / "step_0000000000")
    jdir = os.path.join(snap, journal.JOURNAL_DIRNAME)
    metas = journal.read_epoch_metas(jdir)
    committed = journal.committed_epochs(metas)
    assert [m["epoch"] for m in committed] == [1]
    # The killed epoch left its fence and torn tails behind.
    assert os.path.exists(os.path.join(jdir, journal.FENCE_FNAME))
    offsets = committed[-1]["offsets"]
    torn_before = {
        r: os.path.getsize(os.path.join(jdir, journal.segment_name(int(r))))
        for r in offsets
    }
    assert any(torn_before[r] > offsets[r] for r in offsets), torn_before

    # A fresh world restores bit-exact to epoch 1 on every rank...
    results = run_with_subprocesses(
        _w2_journal_restore_worker, 2, str(tmp_path), timeout=180.0
    )
    for rank, out in results.items():
        assert out["step"] == 0, (rank, out)
        assert out["bit_exact"], (rank, out)
    # ...and replay truncated every torn tail back to the committed
    # offset (the tail is never trusted, never spliced).
    for r in offsets:
        seg = os.path.join(jdir, journal.segment_name(int(r)))
        assert os.path.getsize(seg) == offsets[r], r
    # fsck: only the stale epoch fence remains, and --repair clears it.
    code, report = run_fsck(snap)
    assert code == 1 and report.classes() == {"stale-fence"}, report.findings
    assert run_fsck(snap, repair=True)[0] == 0
    assert run_fsck(snap)[0] == 0


def test_chaos_journal_corrupt_record_falls_back(tmp_path, monkeypatch):
    """A journal record corrupted at append time (CRCs were computed over
    the true bytes, so the damage is on disk inside a COMMITTED epoch):
    replay must CRC-reject the whole journal and restore the base
    snapshot exactly — bounded fallback, no partial splice — and fsck
    must name the unrepairable journal-corrupt-record."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")
    from torchsnapshot_tpu import CheckpointManager

    mgr = CheckpointManager(str(tmp_path), save_interval_steps=100)
    state0 = _state(0)
    mgr.save(0, state0)
    st = _state(0)
    st["model"]["w"] = np.asarray(st["model"]["w"]) + 1.0
    faultinject.configure("journal.append@1=corrupt;seed=31")
    try:
        assert mgr.journal_step(1, st)  # commits — the damage is latent
    finally:
        faultinject.disable()

    dst = _zeros_like(state0)
    assert CheckpointManager(str(tmp_path)).restore(dst) == 0
    assert _equal(dst, state0), "fallback must be the base, bit-exact"
    snap = str(tmp_path / "step_0000000000")
    code, report = run_fsck(snap, repair=True)
    assert code == 1
    assert "journal-corrupt-record" in report.classes()
    assert not report.repaired


def test_chaos_journal_preemption_sigterm_flushes_epoch(tmp_path, monkeypatch):
    """A real SIGTERM mid-epoch (between journal steps): the manager's
    emergency path flushes one final journal epoch instead of a
    synchronous full save, and restore is bit-exact to the preempted
    state."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_JOURNAL", "1")
    from torchsnapshot_tpu import CheckpointManager
    from torchsnapshot_tpu.preemption import PreemptionWatcher

    watcher = PreemptionWatcher()
    try:
        mgr = CheckpointManager(
            str(tmp_path), save_interval_steps=100, preemption=watcher
        )
        state0 = _state(0)
        mgr.save(0, state0)
        st = _state(0)
        st["model"]["w"] = np.asarray(st["model"]["w"]) + 1.0
        assert mgr.journal_step(1, st)
        st["model"]["w"] = np.asarray(st["model"]["w"]) + 1.0
        st["model"]["step"] = np.array([2], dtype=np.int64)
        os.kill(os.getpid(), signal.SIGTERM)
        # Off-cadence save: the flush replaces the full emergency save.
        assert mgr.save(2, st) is False
        assert watcher.consumed
        assert mgr.all_steps() == [0]  # no emergency snapshot directory
    finally:
        watcher.close()

    dst = _zeros_like(st)
    assert CheckpointManager(str(tmp_path)).restore(dst) == 0
    assert _equal(dst, st), "the flushed epoch must restore bit-exact"
    assert run_fsck(str(tmp_path / "step_0000000000"))[0] == 0


# ------------------------------------------- fleet-distribution schedules
#
# The ISSUE 16 seeding drills: a fleet of INDEPENDENT replica restores
# (world-1 process groups over a shared registry store) under peer
# faults. The invariant is the seeding tier's degradation contract:
# every replica restore stays committed-bit-exact — a dead or corrupting
# seeder costs a re-parent and ultimately a direct storage read
# (fanout_fallbacks), never a hang, never poisoned state.


def _seed_fleet_worker(rank: int, world_size: int, root: str, drill: str):
    """One replica of the serving fleet. Rank 0 restores first and arms
    the fault (it is the depth-0 seeder every later fetch elects first);
    rank 1 restores next (the rank that OBSERVES the fault directly);
    ranks 2+ restore last, sourcing from whatever survived."""
    import time as _time

    from torchsnapshot_tpu import distrib, telemetry
    from torchsnapshot_tpu import faultinject as fi
    from torchsnapshot_tpu.pg_wrapper import ProcessGroup, get_default_pg

    os.environ["TORCHSNAPSHOT_TPU_SEED_RESTORE"] = "always"
    telemetry.set_enabled(True)
    store = get_default_pg().store
    distrib.configure_registry(store.clone)
    snap = os.path.join(root, "base")
    expected = _state(7)

    def _restore():
        dst = _zeros_like(expected)
        # A world-1 group: each replica restores INDEPENDENTLY — the
        # fleet overlaps in time but never in a collective.
        Snapshot(snap, pg=ProcessGroup(None, 0, 1)).restore(dst)
        return _equal(dst, expected)

    if rank == 0:
        ok = _restore()  # seeds every shareable chunk at depth 0
        if drill == "kill":
            # Die mid-chunk-transfer on the FIRST serve: the fetcher sees
            # the connection drop, re-parents, and falls back direct.
            fi.configure("distrib.seed_xfer@1=kill")
        else:
            # Corrupt EVERY serve: each fetch from this replica fails the
            # receiver's content-address re-hash and is rejected.
            fi.configure("distrib.seed_xfer@1+=corrupt")
        store.set("seed_ready", b"1")
        if drill == "kill":
            try:  # killed by the fault when rank 1's fetch arrives
                store.get("__never_set__", timeout=90.0)
            except Exception:  # noqa: BLE001 - pragma: no cover
                pass
            return "should-be-dead"  # pragma: no cover
        # Corrupt drill: keep serving (corruptly) until the fleet is done.
        deadline = _time.monotonic() + 90.0
        while store.add("seed_fleet_done", 0) < world_size - 1:
            if _time.monotonic() > deadline:
                raise TimeoutError("fleet never finished restoring")
            _time.sleep(0.05)
        fi.disable()
        counters = telemetry.counters()
    else:
        store.get("seed_ready", timeout=60.0)
        if rank > 1:
            # Restore AFTER rank 1 so a clean survivor seeder exists.
            store.get("seed_r1_done", timeout=90.0)
        ok = _restore()
        if rank == 1:
            store.set("seed_r1_done", b"1")
            if drill == "kill":
                # Rank 0 is dead by now (its kill fired on OUR fetch);
                # cover its exit-barrier share so survivors don't stall.
                store.add("__exit__/count", 1)
        counters = telemetry.counters()
        store.add("seed_fleet_done", 1)
    return {
        "bit_exact": ok,
        "fallbacks": counters.get("fanout_fallbacks", 0),
        "seeded_bytes": counters.get("bytes_from_seeders", 0),
    }


def test_chaos_seed_peer_sigkill_mid_transfer(tmp_path):
    """SIGKILL the depth-0 seeding peer mid-chunk-transfer at w4: the
    fetcher whose transfer died re-parents, finds no live seeder, and
    falls back to a direct storage read; later replicas seed from the
    survivor. Every surviving replica restores committed-bit-exact."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    Snapshot.take(str(tmp_path / "base"), _state(7), replicated=["**"])
    # The registry must outlive rank 0 (the default store host), so the
    # leader runs in a dedicated external process.
    results = run_with_subprocesses(
        _seed_fleet_worker, 4, str(tmp_path), "kill",
        timeout=240.0, expect_dead=(0,), external_store=True,
    )
    assert 0 not in results, results  # the kill actually landed
    assert set(results) == {1, 2, 3}, results
    for rank, out in results.items():
        assert out["bit_exact"], (rank, out)
    # Rank 1's transfer died underneath it: re-parent found nobody, the
    # chunk degraded to a direct read — counted, never a hang.
    assert results[1]["fallbacks"] >= 1, results[1]
    # Later replicas sourced from the surviving seeder, not storage.
    for rank in (2, 3):
        assert results[rank]["seeded_bytes"] > 0, (rank, results[rank])


def test_chaos_seed_corrupt_chunk_rejected_and_reread(tmp_path):
    """A corrupting seeder at w4: every chunk it serves fails the
    receiver's content-address re-hash and is rejected like a CRC
    failure. The first fetcher re-reads direct from storage (and becomes
    a clean seeder); later replicas re-parent past the corruptor to the
    clean copy. No replica ever applies poisoned bytes."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    Snapshot.take(str(tmp_path / "base"), _state(7), replicated=["**"])
    results = run_with_subprocesses(
        _seed_fleet_worker, 4, str(tmp_path), "corrupt", timeout=240.0,
    )
    assert set(results) == {0, 1, 2, 3}, results
    for rank, out in results.items():
        assert out["bit_exact"], (rank, out)
    # Rank 1 had only the corruptor to fetch from: every unit rejected,
    # every unit re-read direct.
    assert results[1]["fallbacks"] >= 1, results[1]
    assert results[1]["seeded_bytes"] == 0, results[1]
    # Ranks 2-3 elected the corruptor first (lowest registration seq),
    # rejected its bytes, and re-parented to rank 1's clean copy.
    for rank in (2, 3):
        assert results[rank]["seeded_bytes"] > 0, (rank, results[rank])


# ---------------------------------------------- geo-replication drills
#
# ISSUE 20: the async shipper's splice fences under kill/corrupt/outage.
# The invariant: the REMOTE tier only ever holds base + a contiguous
# prefix of committed epochs — a dead, corrupting, or refused shipper
# can delay replication, never poison it, and never touch the
# foreground.

_GEOREP_KILL_CHILD = r"""
import os, sys
os.environ["JAX_PLATFORMS"] = "cpu"
import numpy as np
from torchsnapshot_tpu import Snapshot, StateDict, faultinject, georep
from torchsnapshot_tpu.journal import DeltaJournal

root, remote, plan = sys.argv[1], sys.argv[2], sys.argv[3]
step_dir = os.path.join(root, "step_0000000001")
rng = np.random.default_rng(11)
state = {"model": StateDict(
    w=rng.standard_normal(20_000).astype(np.float32),
    step=np.array([0], dtype=np.int64),
)}
Snapshot.take(step_dir, state)
j = DeltaJournal(step_dir, base_step=1, rank=0)
j.capture_baseline(state)
for e in (1, 2):
    state["model"]["w"][: 64 * e] = float(e)
    state["model"]["step"][0] = e
    j.append_epoch(state)
faultinject.configure(plan)
rep = georep.GeoReplicator(remote, interval=0.05)
rep.enqueue(step_dir, 1)
rep.drain(60)
print("SURVIVED")  # only reachable if the plan never fired
"""


def test_chaos_georep_shipper_sigkill_resumes_exactly_once(tmp_path):
    """SIGKILL the shipper mid-stream (epoch 2's blob just read, epoch 1
    already applied): the remote holds base + epoch 1 and a cursor that
    proves it. A resurrected shipper resumes FROM the cursor — one
    segment extension, no re-apply — and the remote then restores every
    committed epoch bit-exact."""
    from torchsnapshot_tpu import georep, journal

    root = str(tmp_path / "primary")
    remote = str(tmp_path / "remote")
    os.makedirs(root)
    os.makedirs(remote)
    r = subprocess.run(
        [sys.executable, "-c", _GEOREP_KILL_CHILD, root, remote,
         "georep.ship@2=kill"],
        capture_output=True,
        text=True,
        timeout=150,
    )
    assert r.returncode == -signal.SIGKILL, (r.returncode, r.stderr)
    assert "SURVIVED" not in r.stdout
    step_dir = os.path.join(root, "step_0000000001")
    remote_step = os.path.join(remote, "step_0000000001")
    cur = georep.read_cursor(remote_step)
    assert cur is not None and cur["epoch"] == 1, cur
    shipped = journal.committed_epochs(
        journal.read_epoch_metas(
            os.path.join(remote_step, journal.JOURNAL_DIRNAME)
        )
    )
    assert [m["epoch"] for m in shipped] == [1]

    # Resurrected shipper: resumes mid-stream, ships ONLY epoch 2.
    rep = georep.GeoReplicator(remote, interval=0.05)
    try:
        rep.enqueue(step_dir, 1)
        assert rep.drain(timeout=30.0), rep.last_error
    finally:
        rep.close(0)
    assert georep.read_cursor(remote_step)["epoch"] == 2
    # Region loss: the remote restores the child's final state bit-exact.
    rng = np.random.default_rng(11)
    w = rng.standard_normal(20_000).astype(np.float32)
    for e in (1, 2):
        w[: 64 * e] = float(e)
    dst = {"model": StateDict(
        w=np.zeros(20_000, dtype=np.float32),
        step=np.array([0], dtype=np.int64),
    )}
    Snapshot(remote_step).restore(dst)
    assert np.array_equal(np.asarray(dst["model"]["w"]), w)
    assert int(dst["model"]["step"][0]) == 2
    assert run_fsck(step_dir)[0] == 0
    assert run_fsck(remote_step)[0] == 0


def test_chaos_georep_corrupt_frame_rejected_and_reshipped(tmp_path):
    """A frame corrupted in flight (after the CRCs were computed over
    the true bytes): the remote applier rejects it without touching a
    byte, and the next cycle re-reads the intact primary journal and
    re-ships clean. The remote never holds the poisoned frame."""
    from torchsnapshot_tpu import georep, journal, telemetry
    from torchsnapshot_tpu.journal import DeltaJournal

    telemetry.set_enabled(True)
    try:
        root = str(tmp_path / "primary")
        remote = str(tmp_path / "remote")
        step_dir = os.path.join(root, "step_0000000001")
        state = _state(3)
        Snapshot.take(step_dir, state)
        j = DeltaJournal(step_dir, base_step=1, rank=0)
        j.capture_baseline(state)
        state["model"]["w"] = np.asarray(state["model"]["w"]) + 1.0
        assert j.append_epoch(state) > 0

        faultinject.configure("georep.ship@1=corrupt;seed=47")
        rep = georep.GeoReplicator(remote, interval=0.05)
        try:
            rep.enqueue(step_dir, 1)
            # The first attempt is rejected; the retry cycle re-ships
            # the intact blob and converges.
            assert rep.drain(timeout=30.0), rep.last_error
        finally:
            rep.close(0)
            faultinject.disable()
        assert telemetry.counters().get("georep_frames_rejected", 0) >= 1

        remote_step = os.path.join(remote, "step_0000000001")
        jdir = os.path.join(remote_step, journal.JOURNAL_DIRNAME)
        committed = journal.committed_epochs(journal.read_epoch_metas(jdir))
        assert [m["epoch"] for m in committed] == [1]
        # Byte-identical to the primary's committed chain: the poisoned
        # frame never spliced.
        local_seg = os.path.join(
            step_dir, journal.JOURNAL_DIRNAME, journal.segment_name(0)
        )
        remote_seg = os.path.join(jdir, journal.segment_name(0))
        assert (
            open(remote_seg, "rb").read() == open(local_seg, "rb").read()
        )
        dst = _zeros_like(state)
        Snapshot(remote_step).restore(dst)
        assert _equal(dst, state)
    finally:
        telemetry.reset()
        telemetry.set_enabled(False)


def test_chaos_georep_remote_outage_bounded_and_foreground_clean(tmp_path):
    """A permanent remote-tier outage at the apply control point: the
    foreground keeps committing (journal appends succeed untouched),
    the backlog stays bounded, and the lag is loud. When the tier
    returns, the shipper converges without operator action."""
    from torchsnapshot_tpu import georep, telemetry
    from torchsnapshot_tpu.journal import DeltaJournal

    telemetry.set_enabled(True)
    try:
        root = str(tmp_path / "primary")
        remote = str(tmp_path / "remote")
        step_dir = os.path.join(root, "step_0000000001")
        state = _state(5)
        Snapshot.take(step_dir, state)
        j = DeltaJournal(step_dir, base_step=1, rank=0)
        j.capture_baseline(state)

        faultinject.configure("georep.apply@1+=permanent")
        rep = georep.GeoReplicator(remote, interval=0.05)
        try:
            # Foreground commits keep landing while every remote apply
            # fails — the shipper absorbs the outage off the hot path.
            for e in (1, 2, 3):
                state["model"]["w"] = np.asarray(state["model"]["w"]) + 1.0
                assert j.append_epoch(state) > 0
                rep.enqueue(step_dir, 1)
            assert not rep.drain(timeout=1.0)
            assert rep.last_error, "the outage must be loud"
            assert rep.backlog_epochs() >= 1
            assert rep.lag_s() > 0.0
            assert telemetry.counters().get("georep_ship_errors", 0) >= 1
            # The tier comes back: convergence needs nothing but time.
            faultinject.disable()
            assert rep.drain(timeout=30.0), rep.last_error
            assert rep.backlog_epochs() == 0
        finally:
            rep.close(0)
            faultinject.disable()
        dst = _zeros_like(state)
        Snapshot(os.path.join(remote, "step_0000000001")).restore(dst)
        assert _equal(dst, state)
    finally:
        telemetry.reset()
        telemetry.set_enabled(False)


def test_matrix_is_large_enough():
    """The acceptance floor: >= 30 deterministic schedules across
    backends and world sizes (kills and w2 drills included)."""
    n = (
        len(FS_TAKE_PLANS)
        + 1  # streamed pwrite
        + len(FS_RESTORE_PLANS)
        + len(S3_TAKE_PLANS)
        + len(S3_RESTORE_PLANS)
        + len(MIRROR_TAKE_PLANS)
        + len(MIRROR_RESTORE_PLANS)
        + len(KILL_PLANS)
        + len(W2_TAKE_PLANS)
        + 2  # w2 restore drill + rpc-death drill
        + 2  # store-host SIGKILL: failover commit + no-replica bounded
        + 3  # delta-journal: w2 SIGKILL mid-append, corrupt record,
        #      preemption-SIGTERM epoch flush (ISSUE 14)
        + 2  # fleet distribution: seed-peer SIGKILL mid-transfer +
        #      corrupt seeded chunk rejected (ISSUE 16)
        + 3  # geo-replication: shipper SIGKILL mid-stream, corrupt
        #      frame rejected + re-shipped, remote-tier outage bounded
        #      (ISSUE 20)
    )
    assert n >= 33, n
