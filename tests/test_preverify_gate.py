"""Distributed-preverify gating: collective flag agreement + economics.

The round-5 advisor's env-skew hazard (ADVICE low #1): dist_verify gated
a PER-KEY collective on each rank's independently-resolved
TORCHSNAPSHOT_TPU_DEVICE_DIGESTS env var, so a skewed rank skipped the
gather while peers entered it — deadlocking the restore until the 1800 s
store timeout. The fix ANDs an up-front all-gathered flag, so skew (env
or the governor's rate-gate diverging) degrades to no-verification.

The test worlds here are REAL 2-process jax.distributed worlds; a
regression hangs, so the launcher timeout is the assertion.
"""

import os
import shutil
import tempfile

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]


def _skew_worker(rank, world_size, root, port, skew):
    # Rank-dependent env BEFORE the restore resolves it: with skew=True
    # rank 1 believes digests are off while rank 0 believes they're on.
    if skew and rank == 1:
        os.environ["TORCHSNAPSHOT_TPU_DEVICE_DIGESTS"] = "0"
    else:
        os.environ["TORCHSNAPSHOT_TPU_DEVICE_DIGESTS"] = "1"

    from torchsnapshot_tpu.test_utils import init_pod_world

    jax = init_pod_world(rank, world_size, port, local_devices=2)
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict

    shape = (64, 128)
    mesh = Mesh(
        np.array(jax.devices()).reshape(world_size, 2), ("proc", "local")
    )

    def mk(spec):
        def cb(index):
            r = np.arange(*index[0].indices(shape[0]), dtype=np.float32)
            c = np.arange(*index[1].indices(shape[1]), dtype=np.float32)
            return r[:, None] * 3.0 + c[None, :]

        return jax.make_array_from_callback(
            shape, NamedSharding(mesh, spec), cb
        )

    # Saved column-wise, restored row-wise: every piece is cut across
    # both processes, so a digest-enabled restore MUST take the
    # distributed-preverify collective when both ranks opt in.
    src = mk(P(None, "local"))
    Snapshot.take(root, {"m": StateDict(w=src)}, device_digests=True)

    dst = StateDict(w=mk(P("proc", None)))
    # device_digests=None: resolved from the (possibly skewed) env.
    Snapshot(root).restore({"m": dst})
    want = np.arange(shape[0], dtype=np.float32)[:, None] * 3.0 + np.arange(
        shape[1], dtype=np.float32
    )
    for shard in dst["w"].addressable_shards:
        assert np.array_equal(np.asarray(shard.data), want[shard.index])
    return "ok"


def test_env_skew_degrades_to_reads_not_deadlock(tmp_path) -> None:
    """Rank 1 without the digest env var: the restore must COMPLETE
    (collective flag agreement ANDs to False -> everyone reads) instead
    of deadlocking at the per-key gather. The 120 s launcher timeout is
    the regression detector (the old behavior hung for 1800 s)."""
    tmp = tempfile.mkdtemp(prefix="preverify_skew_")
    try:
        results = run_with_subprocesses(
            _skew_worker,
            2,
            os.path.join(tmp, "snap"),
            _find_free_port(),
            True,
            timeout=120.0,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    assert results == {0: "ok", 1: "ok"}


def test_no_skew_still_verifies(tmp_path) -> None:
    """Both ranks opted in: the agreed flag stays True and the restore
    still completes (sanity guard that the fix didn't disable the
    verification path outright)."""
    tmp = tempfile.mkdtemp(prefix="preverify_noskew_")
    try:
        results = run_with_subprocesses(
            _skew_worker,
            2,
            os.path.join(tmp, "snap"),
            _find_free_port(),
            False,
            timeout=120.0,
        )
    finally:
        shutil.rmtree(tmp, ignore_errors=True)
    assert results == {0: "ok", 1: "ok"}
