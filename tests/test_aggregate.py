"""Direct unit tests for telemetry/aggregate.py's fleet merge.

The merge was previously exercised only through distributed-take tests;
these pin its edge cases standalone: single-rank fleets, ranks
contributing ``None`` (telemetry disabled there), skewed rank walls, and
the degradation counters (store/mirror/fanout failovers) that the
observability PR wired into the persisted summary.
"""

from __future__ import annotations

from torchsnapshot_tpu.telemetry.aggregate import merge_summaries


def _summary(rank, wall_s, counters=None):
    return {
        "op": "take",
        "rank": rank,
        "wall_s": wall_s,
        "counters": counters or {},
    }


def test_single_rank_fleet():
    fleet = merge_summaries([_summary(0, 1.5, {"bytes_written": 1000})])
    assert fleet["world_size"] == 1
    assert fleet["reporting"] == 1
    assert fleet["slowest_rank"] == 0
    assert fleet["fastest_rank"] == 0
    assert fleet["skew_s"] == 0.0
    assert fleet["aggregate"]["bytes_written"] == 1000
    # Fleet bandwidth over the critical path (the one rank's wall).
    assert abs(fleet["aggregate"]["write_gbps"] - 1000 / 1.5 / 1e9) < 1e-12


def test_none_contributions_are_counted_not_crashed():
    """A rank with telemetry disabled contributes None: the merge must
    report world_size from the GATHER length and how many ranks actually
    reported — never divide by the missing rank or misattribute its
    slot."""
    fleet = merge_summaries(
        [None, _summary(1, 2.0, {"bytes_written": 500}), None]
    )
    assert fleet["world_size"] == 3
    assert fleet["reporting"] == 1
    # Rank identity comes from the gather SLOT, not the reporting order.
    assert fleet["slowest_rank"] == 1
    assert fleet["aggregate"]["bytes_written"] == 500


def test_all_none_returns_none():
    assert merge_summaries([None, None]) is None
    assert merge_summaries([]) is None


def test_skewed_walls_name_slowest_and_fastest():
    """Rank walls are per-rank monotonic intervals (never cross-rank
    clock comparisons): a heavily skewed fleet reports the skew and the
    offenders by rank index."""
    fleet = merge_summaries(
        [
            _summary(0, 1.0, {"bytes_written": 100}),
            _summary(1, 61.0, {"bytes_written": 100}),
            _summary(2, 2.0, {"bytes_written": 100}),
        ]
    )
    assert fleet["slowest_rank"] == 1
    assert fleet["fastest_rank"] == 0
    assert fleet["skew_s"] == 60.0
    assert fleet["wall_s_max"] == 61.0
    # Fleet bandwidth is everyone's bytes over the SLOWEST wall — the
    # time the training loop actually paid.
    assert abs(fleet["aggregate"]["write_gbps"] - 300 / 61.0 / 1e9) < 1e-15


def test_degradation_counters_sum_across_ranks():
    """store_failovers / lease_renewals / fanout_fallbacks /
    mirror_failovers aggregate like byte counters (the PR 6 counters the
    persisted summary used to drop)."""
    fleet = merge_summaries(
        [
            _summary(0, 1.0, {"store_failovers": 1, "fanout_fallbacks": 2}),
            _summary(1, 1.1, {"store_failovers": 1, "mirror_failovers": 3,
                              "lease_renewals": 40}),
        ]
    )
    agg = fleet["aggregate"]
    assert agg["store_failovers"] == 2
    assert agg["fanout_fallbacks"] == 2
    assert agg["mirror_failovers"] == 3
    assert agg["lease_renewals"] == 40


def test_zero_valued_counters_are_elided():
    fleet = merge_summaries(
        [_summary(0, 1.0, {"bytes_written": 0, "retry_attempts": 0})]
    )
    assert fleet["aggregate"] == {}


def test_missing_wall_defaults_to_zero_not_crash():
    fleet = merge_summaries([{"op": "take", "rank": 0, "counters": {}}])
    assert fleet["wall_s_max"] == 0.0
    assert fleet["skew_s"] == 0.0


def test_render_includes_failover_lines():
    """The stats rendering surfaces non-zero degradation counters."""
    from torchsnapshot_tpu.telemetry.export import render_summary_document

    doc = {
        "op": "take",
        "world_size": 2,
        "ranks": [
            _summary(0, 1.0, {"store_failovers": 1}),
            _summary(1, 1.2, {"store_failovers": 1, "fanout_fallbacks": 2}),
        ],
    }
    doc["fleet"] = merge_summaries(doc["ranks"])
    text = render_summary_document(doc)
    assert "failovers:" in text
    assert "store=2" in text
    assert "fanout=2" in text


# ------------------------------------------------------------- histograms


def _hist(counts, total=None, s=0.0):
    return {"counts": counts, "count": total if total is not None
            else sum(counts), "sum": s}


def test_merge_histograms_bucketwise_sum():
    from torchsnapshot_tpu.telemetry.aggregate import merge_histograms

    a = _summary(0, 1.0)
    a["histograms"] = {
        "write.entry_s": {"FS": _hist([1, 0, 2], s=0.5)},
        "collective.wait_s": {"barrier": _hist([1], s=0.1)},
    }
    b = _summary(1, 1.0)
    b["histograms"] = {"write.entry_s": {"FS": _hist([0, 3, 1], s=0.25)}}
    merged = merge_histograms([a, b, None])
    fs = merged["write.entry_s"]["FS"]
    assert fs["counts"] == [1, 3, 3]
    assert fs["count"] == 7
    assert fs["sum"] == 0.75
    # A family only one rank contributed survives untouched.
    assert merged["collective.wait_s"]["barrier"]["counts"] == [1]


def test_merge_histograms_pads_short_counts():
    from torchsnapshot_tpu.telemetry.aggregate import merge_histograms

    a = _summary(0, 1.0)
    a["histograms"] = {"write.entry_s": {"": _hist([1])}}
    b = _summary(1, 1.0)
    b["histograms"] = {"write.entry_s": {"": _hist([0, 0, 5])}}
    merged = merge_histograms([a, b])
    assert merged["write.entry_s"][""]["counts"] == [1, 0, 5]


def test_fleet_view_carries_histograms():
    a = _summary(0, 1.0, {"bytes_written": 10})
    a["histograms"] = {"write.entry_s": {"FS": _hist([2])}}
    fleet = merge_summaries([a, _summary(1, 2.0)])
    assert fleet["histograms"]["write.entry_s"]["FS"]["count"] == 2
    # No histograms anywhere -> the key is absent, not an empty dict.
    fleet = merge_summaries([_summary(0, 1.0)])
    assert "histograms" not in fleet
