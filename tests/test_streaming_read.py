"""Sub-chunk streaming read pipeline tests.

Four layers of coverage, mirroring the contract's seams:

- **Storage-plugin contract** (``CONTRACT_PLUGINS`` — the registry
  ``scripts/check_stream_contract.py`` lints against): for every plugin
  advertising ``supports_streaming_reads`` (fs real, s3/gcs fakes,
  mirror composition) plus the buffered default fallback, a streamed
  read must produce bytes identical to a buffered read of the same
  request (full and ranged), and zero-length ranged reads short-circuit
  inside the plugin.
- **Consumer semantics**: incremental chained CRC accepts/rejects
  exactly like the whole-buffer hash (raw, compressed, and byte-ranged
  slab payloads), a mid-stream exception leaves the destination array
  unmodified, and corruption is detected before anything commits.
- **Scheduler accounting**: streamed entries charge the budget the
  consumer-declared window (per-sub-chunk device_put: 3 sub-chunks;
  direct sliced fills: 2), never the full payload — two entries larger
  than the budget restore concurrently under it.
- **End-to-end**: streamed restores are bit-exact against buffered ones
  for numpy and jax destinations, slab-coalesced restores ride one
  sequential stream, and the mirror failover never splices replica
  bytes after primary bytes (fault injection).
"""

from __future__ import annotations

import asyncio
import os
import subprocess
import sys
import zlib

import numpy as np
import pytest

from torchsnapshot_tpu.io_types import (
    STREAM_DEPTH,
    ReadIO,
    ReadReq,
    ReadStream,
    StoragePlugin,
    StreamRestartRequired,
    WriteIO,
)
from torchsnapshot_tpu.manifest import ArrayEntry
from torchsnapshot_tpu.scheduler import (
    IOGovernor,
    _ReadPipeline,
    execute_read_reqs,
)
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin
from torchsnapshot_tpu.storage_plugins.mirror import MirroredStoragePlugin

SUB = 64 << 10


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


class BufferedFallbackPlugin(StoragePlugin):
    """No read_stream override: exercises the buffered default."""

    def __init__(self):
        self.store = {}

    async def write(self, write_io):
        self.store[write_io.path] = bytes(memoryview(write_io.buf))

    async def read(self, read_io):
        data = self.store[read_io.path]
        if read_io.byte_range is not None:
            lo, hi = read_io.byte_range
            data = data[lo:hi]
        read_io.buf = data

    async def delete(self, path):
        del self.store[path]

    async def close(self):
        pass


def _fs_factory(tmp_path):
    return FSStoragePlugin(str(tmp_path))


def _s3_factory(tmp_path):
    from test_s3_storage_plugin import FakeS3Client, make_plugin

    client = FakeS3Client()

    # The real client answers HEAD for full-object streams.
    def head_object(Bucket, Key):
        return {"ContentLength": len(client.store[(Bucket, Key)])}

    client.head_object = head_object
    return make_plugin(client)


def _gcs_factory(tmp_path):
    from test_gcs_storage_plugin import FakeBucket, make_plugin

    return make_plugin(FakeBucket())


def _mirror_factory(tmp_path):
    return MirroredStoragePlugin(
        FSStoragePlugin(str(tmp_path / "primary")),
        FSStoragePlugin(str(tmp_path / "mirror")),
        ".snapshot_metadata",
    )


def _fallback_factory(tmp_path):
    return BufferedFallbackPlugin()


# Keyed by plugin CLASS name: scripts/check_stream_contract.py asserts
# every in-tree plugin advertising supports_streaming_reads appears here.
CONTRACT_PLUGINS = {
    "FSStoragePlugin": _fs_factory,
    "S3StoragePlugin": _s3_factory,
    "GCSStoragePlugin": _gcs_factory,
    "MirroredStoragePlugin": _mirror_factory,
    "BufferedFallbackPlugin": _fallback_factory,
}


async def _collect(plugin, path, sub_chunk, byte_range=None):
    stream = await plugin.read_stream(
        ReadIO(path=path, byte_range=byte_range), sub_chunk
    )
    parts = []
    async for chunk in stream.chunks:
        parts.append(bytes(memoryview(chunk)))
    return stream.nbytes, parts


# --------------------------------------------------------------- contract


@pytest.mark.parametrize("name", sorted(CONTRACT_PLUGINS))
def test_streamed_equals_buffered(name, tmp_path, loop) -> None:
    plugin = CONTRACT_PLUGINS[name](tmp_path)
    payload = os.urandom(700_000)
    loop.run_until_complete(plugin.write(WriteIO(path="obj", buf=payload)))
    loop.run_until_complete(plugin.drain_background())

    nbytes, parts = loop.run_until_complete(_collect(plugin, "obj", SUB))
    assert nbytes == len(payload)
    assert len(parts) > 1  # genuinely multiple sub-chunks
    assert b"".join(parts) == payload

    # Ranged streams slice exactly like ranged buffered reads.
    nbytes, parts = loop.run_until_complete(
        _collect(plugin, "obj", SUB, byte_range=(1000, 500_000))
    )
    assert nbytes == 499_000
    assert b"".join(parts) == payload[1000:500_000]


@pytest.mark.parametrize("name", sorted(CONTRACT_PLUGINS))
def test_zero_length_ranged_read_short_circuits(name, tmp_path, loop) -> None:
    """Direct plugin users must not hit S3 InvalidRange / GCS 416 on
    empty ranges — each plugin short-circuits before its transport."""
    plugin = CONTRACT_PLUGINS[name](tmp_path)
    payload = b"x" * 1000
    loop.run_until_complete(plugin.write(WriteIO(path="obj", buf=payload)))
    loop.run_until_complete(plugin.drain_background())
    read_io = ReadIO(path="obj", byte_range=(10, 10))
    loop.run_until_complete(plugin.read(read_io))
    assert bytes(read_io.buf) == b""


def test_contract_coverage_lint() -> None:
    """Every plugin advertising supports_streaming_reads is in
    CONTRACT_PLUGINS (no plugin silently opts in without tests)."""
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    script = os.path.join(repo, "scripts", "check_stream_contract.py")
    r = subprocess.run(
        [sys.executable, script], capture_output=True, text=True, timeout=120
    )
    assert r.returncode == 0, r.stderr


# ------------------------------------------------------ consumer semantics


def _entry_for(arr, location="x", checksum=True, codec=None):
    from torchsnapshot_tpu.integrity import compute_checksum
    from torchsnapshot_tpu.serialization import dtype_to_string

    payload = arr.tobytes()
    stored = payload
    entry = ArrayEntry(
        location=location,
        serializer="buffer_protocol",
        dtype=dtype_to_string(arr.dtype),
        shape=list(arr.shape),
        replicated=False,
    )
    if codec is not None:
        stored = zlib.compress(payload, 6)
        entry.codec = codec
    if checksum:
        entry.checksum = compute_checksum(stored)
    return entry, stored


async def _consume_streamed(consumer, stored, sub_chunk, mutate=None):
    data = bytearray(stored)
    if mutate is not None:
        mutate(data)

    async def chunks():
        for lo in range(0, len(data), sub_chunk):
            yield memoryview(data)[lo : lo + sub_chunk]

    await consumer.consume_stream(
        ReadStream(path="x", nbytes=len(data), chunks=chunks())
    )


def test_incremental_crc_equals_whole_buffer_crc(loop) -> None:
    """Streamed consumes record/verify the SAME checksum the buffered
    path does — for raw payloads and across arbitrary chunk cuts."""
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    arr = np.arange(200_000, dtype=np.int32)
    entry, stored = _entry_for(arr)
    for sub in (1000, 7777, 64 << 10):
        dst = np.zeros_like(arr)
        consumer = ArrayBufferConsumer(entry, dst_view=dst)
        assert consumer.can_stream(sub)
        loop.run_until_complete(_consume_streamed(consumer, stored, sub))
        assert np.array_equal(dst, arr)


def test_streamed_corruption_detected_and_dst_unmodified(loop) -> None:
    from torchsnapshot_tpu.integrity import IntegrityError
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    arr = np.arange(200_000, dtype=np.int32)
    entry, stored = _entry_for(arr)
    sentinel = np.full_like(arr, -7)
    dst = sentinel.copy()
    consumer = ArrayBufferConsumer(entry, dst_view=dst)

    def flip(data):
        data[123_456] ^= 0xFF

    with pytest.raises(IntegrityError):
        loop.run_until_complete(
            _consume_streamed(consumer, stored, 10_000, mutate=flip)
        )
    # Verify-before-commit: the destination never saw the corrupt bytes.
    assert np.array_equal(dst, sentinel)


def test_streamed_compressed_payload(loop) -> None:
    """Incremental decompression feeds the same bytes the buffered
    decompress would, and the CRC covers the STORED (compressed) bytes."""
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    arr = np.zeros(300_000, dtype=np.float32)  # compressible
    entry, stored = _entry_for(arr, codec="zlib:6")
    assert len(stored) < arr.nbytes
    dst = np.ones_like(arr)
    consumer = ArrayBufferConsumer(entry, dst_view=dst)
    assert consumer.can_stream(max(1, len(stored) // 4))
    loop.run_until_complete(
        _consume_streamed(consumer, stored, max(1, len(stored) // 4))
    )
    assert np.array_equal(dst, arr)


def test_streamed_compressed_corruption_rejected(loop) -> None:
    from torchsnapshot_tpu.integrity import IntegrityError
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    arr = np.zeros(300_000, dtype=np.float32)
    entry, stored = _entry_for(arr, codec="zlib:6")
    sentinel = np.full_like(arr, 3.0)
    dst = sentinel.copy()
    consumer = ArrayBufferConsumer(entry, dst_view=dst)

    def flip(data):
        data[len(data) // 2] ^= 0xFF

    with pytest.raises((IntegrityError, RuntimeError, zlib.error)):
        loop.run_until_complete(
            _consume_streamed(consumer, stored, max(1, len(stored) // 4), mutate=flip)
        )
    assert np.array_equal(dst, sentinel)


def test_midstream_exception_leaves_destination_unmodified(loop) -> None:
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    arr = np.arange(200_000, dtype=np.int32)
    entry, stored = _entry_for(arr)
    sentinel = np.full_like(arr, 42)
    dst = sentinel.copy()
    consumer = ArrayBufferConsumer(entry, dst_view=dst)

    async def dying_chunks():
        yield memoryview(stored)[:50_000]
        yield memoryview(stored)[50_000:100_000]
        raise RuntimeError("injected mid-stream read failure")

    with pytest.raises(RuntimeError, match="injected"):
        loop.run_until_complete(
            consumer.consume_stream(
                ReadStream(path="x", nbytes=len(stored), chunks=dying_chunks())
            )
        )
    assert np.array_equal(dst, sentinel)


def test_batched_slab_stream_slices_to_consumers(loop) -> None:
    """Cross-entry coalescing: one sequential stream is sliced to the
    per-entry consumers — checksums verify per entry, gaps are skipped,
    and the spanning payload is never materialized."""
    from torchsnapshot_tpu.batcher import batch_read_requests
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    a = np.arange(50_000, dtype=np.int32)
    b = np.arange(70_000, dtype=np.float32) * 0.5
    slab = bytearray(600_000)
    slab[0 : a.nbytes] = a.tobytes()
    b_off = a.nbytes + 4096  # a gap under the merge threshold
    slab[b_off : b_off + b.nbytes] = b.tobytes()

    entry_a, _ = _entry_for(a, location="batched/slab")
    entry_a.byte_range = [0, a.nbytes]
    entry_b, _ = _entry_for(b, location="batched/slab")
    entry_b.byte_range = [b_off, b_off + b.nbytes]

    dst_a, dst_b = np.zeros_like(a), np.zeros_like(b)
    reqs = [
        ReadReq(
            path="batched/slab",
            buffer_consumer=ArrayBufferConsumer(entry_a, dst_view=dst_a),
            byte_range=(0, a.nbytes),
        ),
        ReadReq(
            path="batched/slab",
            buffer_consumer=ArrayBufferConsumer(entry_b, dst_view=dst_b),
            byte_range=(b_off, b_off + b.nbytes),
        ),
    ]
    merged = batch_read_requests(reqs)
    assert len(merged) == 1  # coalesced into one spanning request
    consumer = merged[0].buffer_consumer
    lo, hi = merged[0].byte_range
    assert consumer.can_stream(SUB)
    assert consumer.stream_admission_cost(SUB) < hi - lo

    async def chunks():
        for off in range(lo, hi, SUB):
            yield memoryview(slab)[off : min(off + SUB, hi)]

    loop.run_until_complete(
        consumer.consume_stream(ReadStream(path="batched/slab", nbytes=hi - lo, chunks=chunks()))
    )
    assert np.array_equal(dst_a, a)
    assert np.array_equal(dst_b, b)


# ---------------------------------------------------- scheduler accounting


def _device_consumer(arr, entry):
    import jax
    from jax.sharding import SingleDeviceSharding

    from torchsnapshot_tpu.io_preparers.array import (
        ArrayBufferConsumer,
        DeviceMaterializer,
    )

    restored = []
    sharding = SingleDeviceSharding(jax.devices()[0])
    dest = DeviceMaterializer(
        sharding=sharding,
        dst_dtype=arr.dtype,
        needs_cast=False,
        callback=restored.append,
    )

    # The buffered path's host-array callback, as prepare.py wires it —
    # a buffered fallback (stream restart) must land the array too.
    def materialize(host):
        restored.append(jax.device_put(host, sharding))

    return (
        ArrayBufferConsumer(
            entry,
            callback=materialize,
            ensure_writable=False,
            device_dest=dest,
        ),
        restored,
    )


def test_streamed_budget_charges_window_not_payload() -> None:
    """The acceptance criterion: a streamed large entry's budget charge
    is the sub-chunk window. Device-bound consumers charge chunk +
    read-ahead + row carry; direct sliced fills charge the in-flight
    window; verify-before-commit scratch consumers honestly charge the
    payload they retain — and under the auto policy only stream when
    the storage is measurably latency-bound (``stream_all``)."""
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    arr = np.arange(1_000_000, dtype=np.float32).reshape(1000, 1000)
    entry, _ = _entry_for(arr)

    consumer, _ = _device_consumer(arr, entry)
    pipeline = _ReadPipeline(
        ReadReq(path="x", buffer_consumer=consumer), sub_chunk_bytes=SUB
    )
    assert pipeline.streamed
    assert pipeline.admission_cost_bytes == (STREAM_DEPTH + 1) * SUB
    assert pipeline.admission_cost_bytes < arr.nbytes

    # Scratch consumers (host destination + pending verification) retain
    # the payload: no window win, so auto keeps them on the buffered
    # mmap path unless the storage is latency-bound.
    scratch = ArrayBufferConsumer(entry, dst_view=np.zeros_like(arr))
    pipeline = _ReadPipeline(
        ReadReq(path="x", buffer_consumer=scratch), sub_chunk_bytes=SUB
    )
    assert not pipeline.streamed
    pipeline = _ReadPipeline(
        ReadReq(path="x", buffer_consumer=scratch),
        sub_chunk_bytes=SUB,
        stream_all=True,
    )
    assert pipeline.streamed
    assert pipeline.admission_cost_bytes == arr.nbytes  # honest retention

    # Non-streaming election (no sub-chunk size) charges the payload.
    pipeline = _ReadPipeline(ReadReq(path="x", buffer_consumer=scratch))
    assert not pipeline.streamed
    assert pipeline.admission_cost_bytes == arr.nbytes


def test_sliced_consumer_streams_into_window(loop, tmp_path, monkeypatch) -> None:
    """Budget-split sub-range reads stream as direct fills of assembler
    memory: window admission, correct assembly."""
    from torchsnapshot_tpu.io_preparers.array import ArrayIOPreparer

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(SUB))
    arr = np.arange(500_000, dtype=np.float64)
    entry, stored = _entry_for(arr, location="big", checksum=False)
    plugin = FSStoragePlugin(str(tmp_path))
    loop.run_until_complete(plugin.write(WriteIO(path="big", buf=stored)))

    done = []
    reqs = ArrayIOPreparer.prepare_read(
        entry, callback=done.append, buffer_size_limit_bytes=1 << 20
    )
    assert len(reqs) > 1  # genuinely budget-split
    for req in reqs:
        pipeline = _ReadPipeline(req, sub_chunk_bytes=SUB)
        if pipeline.streamed:
            assert pipeline.admission_cost_bytes <= STREAM_DEPTH * SUB
    loop.run_until_complete(execute_read_reqs(reqs, plugin, 1 << 30, rank=0))
    assert np.array_equal(done[0], arr)


def test_large_entries_restore_concurrently_under_budget(
    loop, tmp_path, monkeypatch
) -> None:
    """Two entries each LARGER than the budget stream concurrently:
    window accounting keeps both admitted where buffered reads would
    serialize through the starvation escape."""
    import jax

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(SUB))

    active = {"now": 0, "peak": 0}

    class TrackingFS(FSStoragePlugin):
        async def read_stream(self, read_io, sub_chunk_bytes):
            inner = await super().read_stream(read_io, sub_chunk_bytes)

            async def chunks():
                active["now"] += 1
                active["peak"] = max(active["peak"], active["now"])
                try:
                    async for chunk in inner.chunks:
                        await asyncio.sleep(0)  # let peers interleave
                        yield chunk
                finally:
                    active["now"] -= 1

            return ReadStream(
                path=inner.path, nbytes=inner.nbytes, chunks=chunks()
            )

    plugin = TrackingFS(str(tmp_path))
    reqs = []
    restored = []
    payload_bytes = 2 << 20
    for i in range(2):
        arr = np.full((512, 1024), float(i), np.float32)  # 2 MB each
        entry, stored = _entry_for(arr, location=f"obj_{i}")
        loop.run_until_complete(
            plugin.write(WriteIO(path=f"obj_{i}", buf=stored))
        )
        consumer, out = _device_consumer(arr, entry)
        restored.append((arr, out))
        reqs.append(ReadReq(path=f"obj_{i}", buffer_consumer=consumer))

    budget = 1 << 20  # half of ONE payload; >= two 3-sub-chunk windows
    assert budget < payload_bytes
    loop.run_until_complete(execute_read_reqs(reqs, plugin, budget, rank=0))
    assert active["peak"] == 2
    for arr, out in restored:
        assert np.array_equal(np.asarray(out[0]), arr)


# ------------------------------------------------------------ mirror fault


class _FlakyPrimaryFS(FSStoragePlugin):
    """Yields one streamed chunk, then dies; buffered reads die too —
    the entry is only recoverable from the mirror tier."""

    async def read_stream(self, read_io, sub_chunk_bytes):
        inner = await super().read_stream(read_io, sub_chunk_bytes)

        async def chunks():
            it = inner.chunks
            yield await it.__anext__()
            await it.aclose()
            raise OSError("injected primary mid-stream death")

        return ReadStream(path=inner.path, nbytes=inner.nbytes, chunks=chunks())

    async def read(self, read_io):
        raise OSError("injected primary read death")


def test_mirror_midstream_failover_never_splices(loop, tmp_path) -> None:
    payload = os.urandom(400_000)
    primary_dir, mirror_dir = tmp_path / "p", tmp_path / "m"
    for d in (primary_dir, mirror_dir):
        loop.run_until_complete(
            FSStoragePlugin(str(d)).write(WriteIO(path="obj", buf=payload))
        )
    mirror = MirroredStoragePlugin(
        _FlakyPrimaryFS(str(primary_dir)),
        FSStoragePlugin(str(mirror_dir)),
        ".snapshot_metadata",
    )

    # Direct stream: a partially-consumed primary raises
    # StreamRestartRequired instead of splicing mirror bytes.
    async def direct():
        stream = await mirror.read_stream(ReadIO(path="obj"), SUB)
        parts = []
        with pytest.raises(StreamRestartRequired):
            async for chunk in stream.chunks:
                parts.append(bytes(memoryview(chunk)))
        return parts

    parts = loop.run_until_complete(direct())
    assert len(parts) == 1  # the primary got exactly one chunk out

    # Scheduler-level: the entry restarts buffered from offset 0 and
    # fails over to the mirror — restored bytes are exact, not spliced.
    arr = np.frombuffer(payload, np.uint8).copy()
    entry, _ = _entry_for(arr, location="obj")
    out = []
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer

    consumer = ArrayBufferConsumer(entry, callback=out.append)
    overrides = {
        "TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES": str(SUB),
        # The host-callback consumer has no window win; force streaming
        # so the restart path is the one under test.
        "TORCHSNAPSHOT_TPU_STREAM_READS": "always",
    }
    saved = {k: os.environ.get(k) for k in overrides}
    os.environ.update(overrides)
    try:
        loop.run_until_complete(
            execute_read_reqs(
                [ReadReq(path="obj", buffer_consumer=consumer)],
                mirror,
                1 << 30,
                rank=0,
            )
        )
    finally:
        for k, v in saved.items():
            if v is None:
                del os.environ[k]
            else:
                os.environ[k] = v
    assert out and out[0].tobytes() == payload


def test_mirror_failover_covers_truncated_primary(loop, tmp_path) -> None:
    """A TORN primary object raises EOFError (not OSError) from the fs
    plugin's short-read guard — the mirror must still fail over."""
    payload = os.urandom(300_000)
    primary = FSStoragePlugin(str(tmp_path / "p"))
    loop.run_until_complete(
        primary.write(WriteIO(path="obj", buf=payload[: len(payload) // 2]))
    )
    mirror_fs = FSStoragePlugin(str(tmp_path / "m"))
    loop.run_until_complete(mirror_fs.write(WriteIO(path="obj", buf=payload)))
    mirror = MirroredStoragePlugin(primary, mirror_fs, ".snapshot_metadata")
    # Ranged read past the truncated primary's size: pread hits EOF.
    read_io = ReadIO(path="obj", byte_range=(0, len(payload)))
    loop.run_until_complete(mirror.read(read_io))
    assert bytes(read_io.buf) == payload


def test_restart_fallback_recharges_budget(loop, tmp_path) -> None:
    """After StreamRestartRequired the buffered retry holds the full
    payload — the pipeline must charge the budget the difference so
    concurrent dispatch throttles instead of overshooting."""
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer
    from torchsnapshot_tpu.scheduler import _MemoryBudget, _Throughput

    payload = os.urandom(400_000)
    for d in ("p", "m"):
        loop.run_until_complete(
            FSStoragePlugin(str(tmp_path / d)).write(
                WriteIO(path="obj", buf=payload)
            )
        )
    mirror = MirroredStoragePlugin(
        _FlakyPrimaryFS(str(tmp_path / "p")),
        FSStoragePlugin(str(tmp_path / "m")),
        ".snapshot_metadata",
    )
    arr = np.frombuffer(payload, np.uint8).copy()
    entry, _ = _entry_for(arr, location="obj")
    consumer, out = _device_consumer(arr, entry)  # windowed admission
    pipeline = _ReadPipeline(
        ReadReq(path="obj", buffer_consumer=consumer), sub_chunk_bytes=SUB
    )
    assert pipeline.streamed
    window = pipeline.admission_cost_bytes
    assert window < len(payload)
    budget = _MemoryBudget(1 << 30)
    budget.acquire(window)
    loop.run_until_complete(
        pipeline.read_and_consume(
            mirror, None, _Throughput("read", 0), budget
        )
    )
    # The fallback re-charged full retention; release symmetry holds.
    assert pipeline.admission_cost_bytes == len(payload)
    assert budget.available == (1 << 30) - len(payload)
    budget.release(pipeline.admission_cost_bytes)
    assert budget.available == 1 << 30
    assert out and np.asarray(out[0]).tobytes() == payload


def test_mirror_zero_produced_failover_is_transparent(loop, tmp_path) -> None:
    """Primary missing up front: the mirror stream starts from offset 0
    with the consumer having seen nothing — no restart needed."""
    payload = os.urandom(300_000)
    mirror_fs = FSStoragePlugin(str(tmp_path / "m"))
    loop.run_until_complete(mirror_fs.write(WriteIO(path="obj", buf=payload)))
    mirror = MirroredStoragePlugin(
        FSStoragePlugin(str(tmp_path / "empty")), mirror_fs, ".snapshot_metadata"
    )
    nbytes, parts = loop.run_until_complete(_collect(mirror, "obj", SUB))
    assert b"".join(parts) == payload


# ------------------------------------------------------------- end to end


def test_restore_streams_and_is_bit_exact(tmp_path, monkeypatch) -> None:
    from torchsnapshot_tpu import Snapshot, StateDict

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(128 << 10))
    arr = np.arange(500_000, dtype=np.float32).reshape(500, 1000)
    state = {"app": StateDict(w=arr, small=np.ones(16, np.float64))}
    Snapshot.take(str(tmp_path / "s"), state)

    # numpy destinations are scratch consumers (no window win): force
    # streaming so this exercises the streamed CRC/consume path.
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", "always")
    dst = {
        "app": StateDict(
            w=np.zeros((500, 1000), np.float32), small=np.zeros(16, np.float64)
        )
    }
    Snapshot(str(tmp_path / "s")).restore(dst)  # streamed (verifies CRC)
    assert np.array_equal(dst["app"]["w"], arr)

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", "0")
    dst2 = {
        "app": StateDict(
            w=np.zeros((500, 1000), np.float32), small=np.zeros(16, np.float64)
        )
    }
    Snapshot(str(tmp_path / "s")).restore(dst2)  # buffered
    assert np.array_equal(dst2["app"]["w"], dst["app"]["w"])


def test_jax_restore_streams_per_chunk_device_put(tmp_path, monkeypatch) -> None:
    import jax
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(128 << 10))
    arr = np.arange(400_000, dtype=np.float32).reshape(400, 1000)
    x = jnp.asarray(arr)
    Snapshot.take(str(tmp_path / "s"), {"app": StateDict(w=x)})
    dst = {"app": StateDict(w=jnp.zeros_like(x))}
    Snapshot(str(tmp_path / "s")).restore(dst)
    assert isinstance(dst["app"]["w"], jax.Array)
    assert np.array_equal(np.asarray(dst["app"]["w"]), arr)


def test_batched_snapshot_restores_through_coalesced_stream(
    tmp_path, monkeypatch
) -> None:
    """Slab-packed snapshots restore through ONE spanning stream per
    slab instead of many ranged reads."""
    from torchsnapshot_tpu import Snapshot, StateDict

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(64 << 10))
    state = {
        "app": StateDict(
            **{
                f"w{i}": np.arange(100_000, dtype=np.float32) + i
                for i in range(4)
            }
        )
    }
    Snapshot.take(str(tmp_path / "s"), state)
    dst = {
        "app": StateDict(
            **{f"w{i}": np.zeros(100_000, np.float32) for i in range(4)}
        )
    }
    Snapshot(str(tmp_path / "s")).restore(dst)
    for i in range(4):
        assert np.array_equal(dst["app"][f"w{i}"], state["app"][f"w{i}"])


def test_stream_reads_mode_parsing(tmp_path, monkeypatch) -> None:
    from torchsnapshot_tpu.scheduler import (
        stream_reads_enabled,
        stream_reads_mode,
    )

    monkeypatch.delenv("TORCHSNAPSHOT_TPU_STREAM_READS", raising=False)
    assert stream_reads_mode() == "auto" and stream_reads_enabled()
    for raw in ("0", "false", "off", "never"):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", raw)
        assert stream_reads_mode() == "never" and not stream_reads_enabled()
    for raw in ("always", "force"):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", raw)
        assert stream_reads_mode() == "always"
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_READS", "1")
    assert stream_reads_mode() == "auto"


def test_latency_bound_storage_streams_full_retention_consumers(
    loop, tmp_path, monkeypatch
) -> None:
    """Auto policy: once the governor measures a latency-bound read
    rate for the plugin, even full-retention scratch consumers stream
    (overlap hides transport latency); memcpy-speed rates keep them on
    the buffered path."""
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer
    from torchsnapshot_tpu.scheduler import io_governor

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(SUB))
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_STREAM_READS", raising=False)

    streamed_calls = {"n": 0}

    class CountingFS(FSStoragePlugin):
        async def read_stream(self, read_io, sub_chunk_bytes):
            streamed_calls["n"] += 1
            return await super().read_stream(read_io, sub_chunk_bytes)

    arr = np.arange(300_000, dtype=np.float32)
    entry, stored = _entry_for(arr, location="obj")
    plugin = CountingFS(str(tmp_path))
    loop.run_until_complete(plugin.write(WriteIO(path="obj", buf=stored)))

    def run_restore():
        dst = np.zeros_like(arr)
        consumer = ArrayBufferConsumer(entry, dst_view=dst)
        loop.run_until_complete(
            execute_read_reqs(
                [ReadReq(path="obj", buffer_consumer=consumer)],
                plugin,
                1 << 30,
                rank=0,
            )
        )
        assert np.array_equal(dst, arr)

    # Fast measured storage: buffered.
    io_governor().record_read("CountingFS", 100 << 30, 1.0)
    run_restore()
    assert streamed_calls["n"] == 0
    # Saturate the EWMA down to a latency-bound rate: streams.
    for _ in range(40):
        io_governor().record_read("CountingFS", 10 << 20, 1.0)
    run_restore()
    assert streamed_calls["n"] == 1


# -------------------------------------------------------------- governor


def test_governor_read_sub_chunk_adapts(monkeypatch) -> None:
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", raising=False)
    gov = IOGovernor()
    assert gov.sub_chunk_bytes(op="read") == 64 << 20  # default
    gov.record_read("FSStoragePlugin", 10 << 30, 1.0)  # 10 GB/s
    assert gov.sub_chunk_bytes("FSStoragePlugin", op="read") == 256 << 20
    # The write-side table must not leak into read sizing.
    gov2 = IOGovernor()
    gov2.record_write("FSStoragePlugin", 10 << 30, 1.0)
    assert gov2.sub_chunk_bytes("FSStoragePlugin", op="read") == 64 << 20
    gov2.record_read("S3StoragePlugin", 50 << 20, 1.0)  # 50 MB/s
    assert gov2.sub_chunk_bytes("S3StoragePlugin", op="read") == 8 << 20
