"""MemoryviewStream tests (reference: tests/test_memoryview_stream.py)."""

import io

import pytest

from torchsnapshot_tpu.memoryview_stream import MemoryviewStream


def test_read_seek_tell() -> None:
    data = bytes(range(100))
    s = MemoryviewStream(memoryview(data))
    assert s.readable() and s.seekable() and not s.writable()
    assert len(s) == 100
    assert s.read(10) == data[:10]
    assert s.tell() == 10
    assert s.read() == data[10:]
    assert s.read(5) == b""
    s.seek(0)
    assert s.read(-1) == data
    s.seek(-10, io.SEEK_END)
    assert s.read() == data[-10:]
    s.seek(20)
    s.seek(5, io.SEEK_CUR)
    assert s.tell() == 25
    with pytest.raises(ValueError):
        s.seek(-1)


def test_readinto() -> None:
    s = MemoryviewStream(memoryview(b"hello world"))
    buf = bytearray(5)
    assert s.readinto(buf) == 5
    assert bytes(buf) == b"hello"


def test_closed() -> None:
    s = MemoryviewStream(memoryview(b"x"))
    s.close()
    with pytest.raises(ValueError):
        s.read()


def test_gcs_s3_plugin_importable() -> None:
    # construction may require credentials/deps; module import must not
    from torchsnapshot_tpu.storage_plugins import gcs, s3  # noqa: F401

    import importlib

    assert importlib.util.find_spec("torchsnapshot_tpu.storage_plugins.s3")
