"""CheckpointManager: cadence, retention, resume (manager.py).

No reference analogue (the ecosystem analogue is orbax's
CheckpointManager); composes the features the rest of the suite covers
individually.
"""

import os

import numpy as np
import pytest

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict


def _state(v: float):
    return StateDict(w=np.full((2048,), v, np.float32), step=int(v))


def _names(root):
    return sorted(
        n
        for n in os.listdir(root)
        if os.path.isfile(os.path.join(root, n, ".snapshot_metadata"))
    )


def test_cadence_and_latest(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=5)
    for step in range(12):
        saved = mgr.save(step, {"app": _state(step)})
        assert saved == (step % 5 == 0), step
    assert mgr.all_steps() == [0, 5, 10]
    assert mgr.latest_step() == 10

    # force saves off-cadence
    mgr.save(12, {"app": _state(12)}, force=True)
    assert mgr.latest_step() == 12


def test_restore_latest_and_specific(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1)
    for step in range(3):
        mgr.save(step, {"app": _state(step)})

    dst = _state(-1)
    restored = mgr.restore({"app": dst})
    assert restored == 2
    np.testing.assert_array_equal(dst["w"], np.full((2048,), 2.0, np.float32))

    dst = _state(-1)
    assert mgr.restore({"app": dst}, step=1) == 1
    assert dst["step"] == 1


def test_keep_last_retention(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, keep_last=2)
    for step in range(5):
        mgr.save(step, {"app": _state(step)})
    assert mgr.all_steps() == [3, 4]


def test_keep_every_archival(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, keep_last=1, keep_every=2
    )
    for step in range(5):
        mgr.save(step, {"app": _state(step)})
    # multiples of 2 survive as archival keeps; newest always survives
    assert mgr.all_steps() == [0, 2, 4]


def test_incremental_chain_bases_survive_retention(tmp_path):
    """keep_last=1 with an incremental chain: the newest snapshot's
    transitive bases must be SPARED (deleting them would break restore),
    and restore from the survivor still works."""
    mgr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, keep_last=1, incremental=True
    )
    # frozen payload identical across saves => every save after the first
    # dedups against its predecessor, chaining back to step_0
    frozen = np.arange(4096, dtype=np.float32)
    for step in range(4):
        state = StateDict(frozen=frozen, head=np.full((8,), float(step)))
        mgr.save(step, {"app": state})

    steps = mgr.all_steps()
    assert 3 in steps  # the kept survivor
    assert 0 in steps  # the chain's physical payload holder, spared
    dst = StateDict(frozen=np.zeros(4096, np.float32), head=np.zeros(8))
    assert mgr.restore({"app": dst}) == 3
    np.testing.assert_array_equal(dst["frozen"], frozen)
    np.testing.assert_array_equal(dst["head"], np.full((8,), 3.0))


def test_async_save_single_inflight_and_wait(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, async_save=True, keep_last=2
    )
    for step in range(4):
        mgr.save(step, {"app": _state(step)})
    mgr.wait()
    assert mgr.all_steps() == [2, 3]
    dst = _state(-1)
    assert mgr.restore({"app": dst}) == 3


def test_resume_discovers_existing_snapshots(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, incremental=True
    )
    for step in range(2):
        mgr.save(step, {"app": _state(step)})

    # a NEW manager (fresh process) picks up where the old one left off:
    # latest_step discovered, incremental chains against it
    mgr2 = CheckpointManager(
        str(tmp_path), save_interval_steps=1, incremental=True
    )
    assert mgr2.latest_step() == 1
    mgr2.save(2, {"app": _state(1)})  # same content as step 1 => dedups
    meta = Snapshot(mgr2.path_for(2)).metadata
    from torchsnapshot_tpu.cli import _entry_payloads

    origins = [
        o
        for e in meta.manifest.values()
        for _, _, _, _, o in _entry_payloads(e)
    ]
    assert any(o is not None for o in origins), "must chain to step 1"


def test_compression_and_options_pass_through(tmp_path):
    mgr = CheckpointManager(
        str(tmp_path), save_interval_steps=1, compression="zlib:1"
    )
    state = StateDict(w=np.arange(100_000, dtype=np.float32))
    mgr.save(0, {"app": state})
    meta = Snapshot(mgr.path_for(0)).metadata
    codecs = [
        sub.array.codec
        for e in meta.manifest.values()
        for sub in getattr(e, "chunks", []) or []
    ]
    assert any(c and c.startswith("zlib") for c in codecs)


def test_validation_errors(tmp_path):
    with pytest.raises(ValueError, match="save_interval_steps"):
        CheckpointManager(str(tmp_path), save_interval_steps=0)
    with pytest.raises(ValueError, match="keep_last"):
        CheckpointManager(str(tmp_path), keep_last=0)
    mgr = CheckpointManager(str(tmp_path))
    with pytest.raises(ValueError, match="step must be"):
        mgr.path_for(-1)
    with pytest.raises(RuntimeError, match="no committed snapshots"):
        mgr.restore({"app": _state(0)})


def test_failed_async_save_raises_on_next_save(tmp_path, monkeypatch):
    from torchsnapshot_tpu.snapshot import SNAPSHOT_METADATA_FNAME
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    class Faulty(FSStoragePlugin):
        # The fence is planted synchronously at plan time; failing it
        # would fail save(0) itself. This test targets the BACKGROUND
        # payload-write failure surfacing on the next save.
        async def write(self, write_io) -> None:
            if write_io.path != SNAPSHOT_METADATA_FNAME and not (
                write_io.path.endswith(".snapshot_fence")
            ):
                raise RuntimeError("injected storage failure")
            await super().write(write_io)

    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, async_save=True)
    monkeypatch.setattr(
        "torchsnapshot_tpu.storage_plugins.fs.FSStoragePlugin", Faulty
    )
    mgr.save(0, {"app": _state(0)})
    monkeypatch.undo()
    with pytest.raises(RuntimeError, match="injected storage failure"):
        mgr.save(1, {"app": _state(1)})  # drains the failed pending first
    # the failed save never committed
    assert mgr.all_steps() == []


def test_resume_step_is_never_overwritten(tmp_path):
    """README resume recipe: the loop re-runs the restored step; a
    re-save must NOT overwrite the committed snapshot (non-atomic, and
    under incremental it would dedup against itself)."""
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1,
                            incremental=True)
    mgr.save(0, {"app": _state(0)})

    mgr2 = CheckpointManager(str(tmp_path), save_interval_steps=1,
                             incremental=True)
    assert mgr2.latest_step() == 0
    assert mgr2.save(0, {"app": _state(99)}) is False  # skipped
    dst = _state(-1)
    mgr2.restore({"app": dst})
    assert dst["step"] == 0  # the original survived untouched
    assert mgr2.save(1, {"app": _state(1)}) is True


def test_foreign_snapshot_names_not_deleted(tmp_path):
    """Snapshots the manager didn't name (unpadded, other tools) are
    invisible to discovery and NEVER deleted by retention."""
    foreign = tmp_path / "step_123"  # unpadded: not manager-named
    Snapshot.take(str(foreign), {"app": _state(7)})
    mgr = CheckpointManager(str(tmp_path), save_interval_steps=1, keep_last=1)
    assert mgr.all_steps() == []  # not discovered
    for step in range(3):
        mgr.save(step, {"app": _state(step)})
    assert mgr.all_steps() == [2]
    assert (foreign / ".snapshot_metadata").exists()  # untouched


def test_mirror_url_is_per_step(tmp_path):
    """A configured mirror_url is the mirror ROOT: each step must mirror
    into its own subdirectory (a shared directory would overwrite the
    previous step's replica in place), and restore's mirror fallback
    must look in the right one."""
    mirror_root = tmp_path / "mirror"
    mgr = CheckpointManager(
        str(tmp_path / "primary"), save_interval_steps=1,
        storage_options={"mirror_url": str(mirror_root)},
    )
    for step in range(2):
        mgr.save(step, {"app": _state(step)})

    # each step has its own complete, independently restorable replica
    for step in range(2):
        mdir = mirror_root / f"step_{step:010d}"
        assert (mdir / ".snapshot_metadata").exists()
        dst = _state(-1)
        Snapshot(str(mdir)).restore({"app": dst})
        assert dst["step"] == step

    # primary loses a payload; restore falls back to THAT step's mirror
    victims = [
        os.path.join(r, f)
        for r, _, fs in os.walk(tmp_path / "primary" / "step_0000000001")
        for f in fs
        if f != ".snapshot_metadata"
    ]
    assert victims
    for v in victims:
        os.remove(v)
    dst = _state(-1)
    assert mgr.restore({"app": dst}, step=1) == 1
    assert dst["step"] == 1


def _committed_skip_worker(rank, world_size, roots):
    """Per-rank (NON-shared) roots: only rank 0's root carries the
    committed step_0 snapshot, so a rank-local `step in all_steps()`
    check would make rank 0 skip while peers enter the collective
    Snapshot.take and hang. The decision must be rank 0's, broadcast."""
    from torchsnapshot_tpu.manager import CheckpointManager
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    mgr = CheckpointManager(roots[rank], pg=get_default_pg())
    saved = mgr.save(0, {"app": _state(0.0)})
    return saved


@pytest.mark.multiprocess
def test_committed_skip_is_rank0_broadcast(tmp_path):
    """A prior run committed step 0 on rank 0's root only; every rank of
    the resumed world must uniformly skip re-saving it (no hang, no
    non-atomic overwrite)."""
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    world = 2
    roots = [str(tmp_path / f"rank{r}") for r in range(world)]
    # Seed rank 0's root with a committed step_0 from a "previous run".
    CheckpointManager(roots[0]).save(0, {"app": _state(0.0)})
    assert _names(roots[0]) == ["step_0000000000"]

    results = run_with_subprocesses(_committed_skip_worker, world, roots)
    assert results == {0: False, 1: False}


def test_warmup_noop_under_incremental_or_compression(tmp_path):
    """The staging pool only feeds the fused (no-dedup, no-codec) path;
    warming it under incremental/compression would pin unused memory."""
    # Prime-sized array: the process-global pool can't already hold a
    # recycled slab of this size from earlier tests.
    state = {"app": StateDict(w=np.zeros(100003, np.uint8))}
    assert CheckpointManager(str(tmp_path / "a"), incremental=True).warmup(state) == 0
    assert (
        CheckpointManager(str(tmp_path / "b"), compression="zlib:6").warmup(state)
        == 0
    )
    warmed = CheckpointManager(str(tmp_path / "c")).warmup(state)
    from torchsnapshot_tpu._native import native_available
    from torchsnapshot_tpu.integrity import checksums_enabled

    if native_available() and checksums_enabled():
        assert warmed > 0


def test_gc_reclaims_mirror_tier_partials(tmp_path):
    """A crashed mirrored save leaves TWO partial trees — the primary
    step dir and its replica under the mirror root. The fenced GC on the
    next save must reclaim both, or crash/retry cycles leak unreferenced
    payloads on the mirror tier forever."""
    primary_root = tmp_path / "primary"
    mirror_root = tmp_path / "mirror"
    step0 = "step_0000000000"
    for root in (primary_root, mirror_root):
        os.makedirs(root / step0 / "0" / "app")
        (root / step0 / "0" / "app" / "junk_0").write_bytes(b"\x00" * 256)
        (root / step0 / ".snapshot_fence").write_text('{"gen": "dead"}')

    mgr = CheckpointManager(
        str(primary_root),
        save_interval_steps=1,
        storage_options={"mirror_url": str(mirror_root)},
    )
    mgr.save(0, {"app": _state(0)})
    # Both partials reclaimed, then re-taken and committed on each tier.
    assert os.path.exists(primary_root / step0 / ".snapshot_metadata")
    assert os.path.exists(mirror_root / step0 / ".snapshot_metadata")
    assert not os.path.exists(primary_root / step0 / "0" / "app" / "junk_0")
    assert not os.path.exists(mirror_root / step0 / "0" / "app" / "junk_0")


def test_gc_spares_mirror_of_committed_step(tmp_path):
    """The mirror's metadata commit is deferred to close() and
    suppressed after any mirror write failure, so a COMMITTED primary
    step can own a metadata-less mirror tree. That tree is degraded
    failover redundancy for the resume point — the GC must never
    reclaim it (only mirror dirs whose primary is also uncommitted)."""
    primary_root = tmp_path / "primary"
    mirror_root = tmp_path / "mirror"
    mgr = CheckpointManager(
        str(primary_root),
        save_interval_steps=1,
        storage_options={"mirror_url": str(mirror_root)},
    )
    mgr.save(0, {"app": _state(0)})
    step0 = "step_0000000000"
    assert os.path.exists(primary_root / step0 / ".snapshot_metadata")
    # Simulate a crash before the mirror's deferred metadata commit.
    os.remove(mirror_root / step0 / ".snapshot_metadata")
    mirrored_payloads = [
        os.path.join(dp, f)
        for dp, _, fs in os.walk(mirror_root / step0)
        for f in fs
    ]
    assert mirrored_payloads, "mirror tier should hold replica payloads"

    mgr.save(1, {"app": _state(1)})
    for p in mirrored_payloads:
        assert os.path.exists(p), (
            "GC reclaimed the mirror replica of a committed step"
        )
