"""Cooperative restore fan-out: end-to-end multi-process coverage.

Real worlds (KV-store rendezvous subprocesses, CPU backend): the full
election → plan → partition → forward → consume pipeline, with the
acceptance-criteria properties asserted directly:

- a 2-process cooperative restore of replicated-majority state is
  bit-exact and reads each replicated payload from storage ~ONCE fleet-
  wide (vs ~world× under direct reads — measured by counting the bytes
  the fs plugin actually serves under ``replicated/``);
- env skew (one rank ``never``) degrades the whole fleet to direct
  reads — completion, not a hang, is the assertion;
- a 3-deep incremental chain restores origin-bearing entries from the
  BASE snapshot's storage whether the bytes arrive via storage or via a
  peer, bit-exact at world sizes 1 and 2;
- an owner whose peer channel dies mid-entry leaves non-owners on
  direct reads and the restore completes bit-exact (fault injection).
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]

SUB = 64 << 10


def _install_read_counter():
    """Count payload bytes the fs plugin actually serves, keyed by the
    plugin's root directory — the measured side of the amplification
    ratio (buffered reads + streamed windows both counted)."""
    from torchsnapshot_tpu.io_types import ReadStream
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    counts: dict = {}

    def add(root, path, n):
        if "replicated/" in path or "sharded/" in path:
            counts[root] = counts.get(root, 0) + n

    orig_read = FSStoragePlugin.read

    async def counting_read(self, read_io, _orig=orig_read):
        await _orig(self, read_io)
        add(self.root, read_io.path, memoryview(read_io.buf).nbytes)

    orig_stream = FSStoragePlugin.read_stream

    async def counting_stream(self, read_io, sub_chunk, _orig=orig_stream):
        inner = await _orig(self, read_io, sub_chunk)
        root = self.root

        async def chunks():
            async for c in inner.chunks:
                add(root, read_io.path, memoryview(c).nbytes)
                yield c

        return ReadStream(path=inner.path, nbytes=inner.nbytes, chunks=chunks())

    FSStoragePlugin.read = counting_read
    FSStoragePlugin.read_stream = counting_stream
    return counts


def _state(seed: int, n_arrays: int = 4, kb_each: int = 384):
    rng = np.random.default_rng(seed)
    return {
        f"w{i}": rng.standard_normal(kb_each * 256 // 4 * 4).astype(np.float32)
        for i in range(n_arrays)
    }


def _payload_bytes(state) -> int:
    return sum(v.nbytes for v in state.values())


def _coop_worker(rank, world_size, root, mode_by_rank):
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = mode_by_rank[rank]
    os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"] = str(SUB)
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"

    from torchsnapshot_tpu import Snapshot, StateDict

    state = _state(seed=7)
    Snapshot.take(root, {"model": StateDict(**state)}, replicated=["model/**"])

    counts = _install_read_counter()
    dst = {"model": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    Snapshot(root).restore(dst)
    for k, v in state.items():
        assert dst["model"][k].tobytes() == v.tobytes(), f"{k} not bit-exact"
    return {"payload_read": sum(counts.values())}


def test_coop_restore_bit_exact_with_single_read_amplification(tmp_path) -> None:
    """COOP_RESTORE=always at world 2: bit-exact, and the fleet reads
    each replicated byte from storage ~once (≤1.2× with headroom for
    rounding), where direct reads serve ~2×."""
    payload = _payload_bytes(_state(seed=7))
    results = run_with_subprocesses(
        _coop_worker, 2, str(tmp_path / "snap"), ("always", "always"),
        timeout=180.0,
    )
    fleet_read = sum(r["payload_read"] for r in results.values())
    assert fleet_read <= 1.2 * payload, (
        f"cooperative restore amplification {fleet_read / payload:.2f}x "
        f"(fleet read {fleet_read} of {payload} payload bytes)"
    )
    # Every byte still has to come from storage exactly once.
    assert fleet_read >= payload


def test_direct_restore_reads_n_times(tmp_path) -> None:
    """The baseline the fan-out removes: never-mode reads ~world×."""
    payload = _payload_bytes(_state(seed=7))
    results = run_with_subprocesses(
        _coop_worker, 2, str(tmp_path / "snap"), ("never", "never"),
        timeout=180.0,
    )
    fleet_read = sum(r["payload_read"] for r in results.values())
    assert fleet_read >= 1.8 * payload


def test_env_skew_degrades_to_direct_reads_not_hang(tmp_path) -> None:
    """Rank 1 opted out: the unanimous-AND election must disable
    cooperation everywhere and the restore must COMPLETE (the launcher
    timeout is the regression detector) with full direct reads."""
    payload = _payload_bytes(_state(seed=7))
    results = run_with_subprocesses(
        _coop_worker, 2, str(tmp_path / "snap"), ("always", "never"),
        timeout=180.0,
    )
    fleet_read = sum(r["payload_read"] for r in results.values())
    assert fleet_read >= 1.8 * payload


# ------------------------------------------------------- incremental chain


def _chain_states():
    v0 = _state(seed=11, n_arrays=3, kb_each=256)
    v1 = dict(v0)
    v1["w1"] = _state(seed=12, n_arrays=3, kb_each=256)["w1"]
    v2 = dict(v1)
    v2["w2"] = _state(seed=13, n_arrays=3, kb_each=256)["w2"]
    return v0, v1, v2


def _take_chain(base_dir):
    from torchsnapshot_tpu import Snapshot, StateDict

    v0, v1, v2 = _chain_states()
    roots = [os.path.join(base_dir, f"snap{i}") for i in range(3)]
    Snapshot.take(
        roots[0], {"model": StateDict(**v0)}, replicated=["model/**"],
        record_digests=True,
    )
    Snapshot.take(
        roots[1], {"model": StateDict(**v1)}, replicated=["model/**"],
        incremental_base=roots[0],
    )
    Snapshot.take(
        roots[2], {"model": StateDict(**v2)}, replicated=["model/**"],
        incremental_base=roots[1],
    )
    return roots, v2


def _chain_worker(rank, world_size, base_dir):
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"] = str(SUB)
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"

    from torchsnapshot_tpu import Snapshot

    roots, v2 = _take_chain(base_dir)
    counts = _install_read_counter()
    from torchsnapshot_tpu import StateDict

    dst = {"model": StateDict(**{k: np.zeros_like(v) for k, v in v2.items()})}
    Snapshot(roots[2]).restore(dst)
    for k, v in v2.items():
        assert dst["model"][k].tobytes() == v.tobytes(), f"{k} not bit-exact"
    # Report per-origin-root bytes: origin-bearing entries MUST have been
    # served by the base snapshots' storage.
    return {os.path.realpath(root): n for root, n in counts.items()}


def test_incremental_chain_coop_world2(tmp_path) -> None:
    """3-deep chain at world 2 under cooperation: origin-bearing entries
    fetch from the BASE snapshots' storage whether the bytes arrive via
    storage or via a peer — and still only ~once fleet-wide."""
    results = run_with_subprocesses(
        _chain_worker, 2, str(tmp_path), timeout=240.0
    )
    v0, v1, v2 = _chain_states()
    payload = sum(v.nbytes for v in v2.values())
    merged: dict = {}
    for r in results.values():
        for root, n in r.items():
            merged[root] = merged.get(root, 0) + n
    fleet_read = sum(merged.values())
    assert fleet_read <= 1.2 * payload, (
        f"chain amplification {fleet_read / payload:.2f}x ({merged})"
    )
    # w0 is unchanged since snap0 and w1 since snap1: both base roots
    # must have served bytes (transitive origin resolution).
    base0 = next((n for root, n in merged.items() if root.endswith("snap0")), 0)
    base1 = next((n for root, n in merged.items() if root.endswith("snap1")), 0)
    assert base0 >= v0["w0"].nbytes
    assert base1 >= v1["w1"].nbytes


def test_incremental_chain_coop_world1(tmp_path) -> None:
    """Same chain at world size 1 with COOP_RESTORE=always: cooperation
    never engages (nothing to share) and the direct path is bit-exact."""
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "always"
    try:
        from torchsnapshot_tpu import Snapshot, StateDict

        roots, v2 = _take_chain(str(tmp_path))
        dst = {
            "model": StateDict(**{k: np.zeros_like(v) for k, v in v2.items()})
        }
        Snapshot(roots[2]).restore(dst)
        for k, v in v2.items():
            assert dst["model"][k].tobytes() == v.tobytes()
    finally:
        os.environ.pop("TORCHSNAPSHOT_TPU_COOP_RESTORE", None)


# ------------------------------------------------------ peer-death drill


def _owner_death_worker(rank, world_size, root):
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES"] = str(SUB)
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"

    from torchsnapshot_tpu import Snapshot, StateDict

    state = _state(seed=23)
    Snapshot.take(root, {"model": StateDict(**state)}, replicated=["model/**"])

    if rank == 0:
        # Data-plane death: after the first forwarded chunk frame, close
        # every outbound peer socket. Rank 0's own restore (and its
        # collectives) stay alive — receivers see an unclean drop, mark
        # the source dead, and direct-read its units.
        from torchsnapshot_tpu import fanout

        orig = fanout.CoopRestoreSession._send_one
        sent = {"n": 0}

        def dying_send(self, r, header, payload, _orig=orig):
            if header.get("op") == "chunk":
                sent["n"] += 1
                if sent["n"] == 2:
                    for sock, lock in self._out.values():
                        try:
                            sock.close()
                        except OSError:
                            pass
            _orig(self, r, header, payload)

        fanout.CoopRestoreSession._send_one = dying_send

    counts = _install_read_counter()
    dst = {"model": StateDict(**{k: np.zeros_like(v) for k, v in state.items()})}
    Snapshot(root).restore(dst)
    for k, v in state.items():
        assert dst["model"][k].tobytes() == v.tobytes(), f"{k} not bit-exact"
    return {"payload_read": sum(counts.values())}


def test_owner_channel_death_falls_back_bit_exact(tmp_path) -> None:
    """Kill the owner's peer channel mid-entry: non-owners fall back to
    direct storage reads and the restore completes bit-exact — promptly
    (the fallback is death-driven, not timeout-driven)."""
    results = run_with_subprocesses(
        _owner_death_worker, 2, str(tmp_path / "snap"), timeout=180.0
    )
    payload = _payload_bytes(_state(seed=23))
    # Rank 1 had to re-read rank 0's partition directly after the drop.
    assert results[1]["payload_read"] > 0
    fleet_read = sum(r["payload_read"] for r in results.values())
    assert fleet_read >= payload
