"""Payload compression: entry-recorded codecs (compression.py).

No reference analogue (the reference stores raw serialized bytes only);
the interaction matrix mirrors the house style of test_incremental.py /
test_mirror_storage.py.
"""

import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.compression import (
    COMPRESSION_ENV_VAR,
    UnknownCodecError,
    compress,
    decompress,
    resolve_codec,
)
from torchsnapshot_tpu.manifest import SnapshotMetadata


def _compressible_state(n=200_000, v=1.0):
    # arange fp32 compresses well; that's the point of the fixture
    return StateDict(
        w=np.arange(n, dtype=np.float32) * v,
        b=np.zeros(n // 2, np.float32) + v,
        step=int(v),
    )


def _payload_bytes(root):
    total = 0
    for r, _, fs in os.walk(root):
        for f in fs:
            if f != ".snapshot_metadata":
                total += os.path.getsize(os.path.join(r, f))
    return total


def _entry_codecs(path):
    from torchsnapshot_tpu.cli import _entry_payloads

    meta = Snapshot(path).metadata
    out = {}
    for p, e in meta.manifest.items():
        for location, _, _, _, _ in _entry_payloads(e):
            out[location] = getattr(e, "codec", None)
    # chunk/shard sub-entries carry their own codec
    for p, e in meta.manifest.items():
        for attr in ("chunks", "shards"):
            for sub in getattr(e, attr, []) or []:
                out[sub.array.location] = sub.array.codec
    return out


def test_resolve_codec_validation():
    assert resolve_codec(None) is None
    assert resolve_codec("none") is None
    assert resolve_codec("off") is None
    assert resolve_codec("zlib") == "zlib:6"
    assert resolve_codec("zlib:1") == "zlib:1"
    assert resolve_codec("zstd") == "zstd:3"
    assert resolve_codec("ZSTD:9") == "zstd:9"
    with pytest.raises(ValueError, match="unknown compression codec"):
        resolve_codec("lz77")
    with pytest.raises(ValueError, match="zlib level"):
        resolve_codec("zlib:42")


def test_compress_decompress_primitives():
    data = bytes(range(256)) * 100
    for codec in ("zstd:3", "zlib:6"):
        packed = compress(codec, data)
        assert len(packed) < len(data)
        assert bytes(decompress(codec, packed, expected_size=len(data))) == data
    with pytest.raises(UnknownCodecError):
        decompress("snappy:1", b"xx")


@pytest.mark.parametrize("codec", ["zstd", "zlib:1"])
def test_round_trip_and_bytes_reduction(tmp_path, codec):
    state = _compressible_state()
    raw_root, comp_root = str(tmp_path / "raw"), str(tmp_path / "comp")
    Snapshot.take(raw_root, {"app": state})
    Snapshot.take(comp_root, {"app": state}, compression=codec)

    raw_bytes, comp_bytes = _payload_bytes(raw_root), _payload_bytes(comp_root)
    assert comp_bytes < raw_bytes / 2, (raw_bytes, comp_bytes)

    recorded = [c for c in _entry_codecs(comp_root).values() if c]
    assert recorded and all(c.startswith(codec.split(":")[0]) for c in recorded)

    # restore verifies checksums (over stored/compressed bytes) + content
    dst = _compressible_state(v=0.0)
    Snapshot(comp_root).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])
    np.testing.assert_array_equal(dst["b"], state["b"])
    assert dst["step"] == 1

    # structure-free read path decompresses too, and arrays are writable
    loaded = Snapshot(comp_root).read_state_dict(key="app")
    np.testing.assert_array_equal(loaded["w"], state["w"])
    assert loaded["w"].flags["WRITEABLE"]


def test_incompressible_payloads_stored_raw(tmp_path):
    rng = np.random.default_rng(0)
    state = StateDict(noise=rng.integers(0, 2**32, 100_000, np.uint32))
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": state}, compression="zstd")
    assert not any(_entry_codecs(root).values())  # raw: no size win
    dst = StateDict(noise=np.zeros(100_000, np.uint32))
    Snapshot(root).restore({"app": dst})
    np.testing.assert_array_equal(dst["noise"], state["noise"])


def test_small_payloads_skip_compression(tmp_path):
    state = StateDict(tiny=np.arange(16, dtype=np.float32))
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": state}, compression="zstd")
    assert not any(_entry_codecs(root).values())


def test_env_var_enables_compression(tmp_path, monkeypatch):
    monkeypatch.setenv(COMPRESSION_ENV_VAR, "zlib:9")
    root = str(tmp_path / "s")
    state = _compressible_state()
    Snapshot.take(root, {"app": state})
    assert any(
        c and c.startswith("zlib") for c in _entry_codecs(root).values()
    )
    monkeypatch.delenv(COMPRESSION_ENV_VAR)
    dst = _compressible_state(v=0.0)
    Snapshot(root).restore({"app": dst})  # restore needs no env
    np.testing.assert_array_equal(dst["w"], state["w"])


def test_invalid_codec_raises_before_io(tmp_path):
    with pytest.raises(ValueError, match="unknown compression codec"):
        Snapshot.take(str(tmp_path / "s"), {"app": _compressible_state()},
                      compression="rle")
    assert not os.path.exists(tmp_path / "s" / ".snapshot_metadata")


def test_unknown_codec_on_restore_is_a_clear_error(tmp_path):
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": _compressible_state()}, compression="zlib")
    meta_path = os.path.join(root, ".snapshot_metadata")
    doctored = open(meta_path).read().replace("zlib:6", "futurecodec:1")
    open(meta_path, "w").write(doctored)
    dst = _compressible_state(v=0.0)
    with pytest.raises(UnknownCodecError, match="futurecodec"):
        Snapshot(root).restore({"app": dst})


def test_async_take_with_compression(tmp_path):
    state = _compressible_state()
    pending = Snapshot.async_take(
        str(tmp_path / "s"), {"app": state}, compression="zstd"
    )
    pending.wait()
    dst = _compressible_state(v=0.0)
    Snapshot(str(tmp_path / "s")).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])


def test_incremental_chain_stable_across_codec_changes(tmp_path):
    """Digests cover UNCOMPRESSED bytes: a raw base still elides writes
    for a compressed incremental (and vice versa), and the deduplicated
    entries carry the BASE's stored checksum/codec so restore reads the
    base's actual bytes correctly."""
    base_raw = str(tmp_path / "base_raw")
    inc_zstd = str(tmp_path / "inc_zstd")
    state = _compressible_state()
    Snapshot.take(base_raw, {"app": state}, record_digests=True)
    Snapshot.take(inc_zstd, {"app": state}, incremental_base=base_raw,
                  compression="zstd")
    # unchanged payloads elided in the incremental
    assert _payload_bytes(inc_zstd) < _payload_bytes(base_raw) / 10
    # deduplicated entries inherit the base's (raw) codec, i.e. none
    codecs = _entry_codecs(inc_zstd)
    assert not any(codecs.values()), codecs
    dst = _compressible_state(v=0.0)
    Snapshot(inc_zstd).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])

    # now the other direction: compressed base, raw incremental re-save
    base_z = str(tmp_path / "base_z")
    inc_raw = str(tmp_path / "inc_raw")
    Snapshot.take(base_z, {"app": state}, record_digests=True,
                  compression="zstd")
    Snapshot.take(inc_raw, {"app": state}, incremental_base=base_z)
    codecs = _entry_codecs(inc_raw)
    assert any(codecs.values()), (
        "deduplicated entries must record the base's zstd codec"
    )
    dst = _compressible_state(v=0.0)
    Snapshot(inc_raw).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])
    np.testing.assert_array_equal(dst["b"], state["b"])


def test_incremental_changed_payloads_compress(tmp_path):
    base, inc = str(tmp_path / "b"), str(tmp_path / "i")
    state = _compressible_state()
    Snapshot.take(base, {"app": state}, record_digests=True, compression="zstd")
    state2 = _compressible_state()
    state2["w"] = state2["w"] + 1.0  # changed -> rewritten, compressed
    Snapshot.take(inc, {"app": state2}, incremental_base=base,
                  compression="zstd")
    codecs = _entry_codecs(inc)
    assert any(c and c.startswith("zstd") for c in codecs.values())
    dst = _compressible_state(v=0.0)
    Snapshot(inc).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state2["w"])


def test_compression_with_mirror_both_tiers(tmp_path):
    primary, mirror = str(tmp_path / "fast"), str(tmp_path / "durable")
    state = _compressible_state()
    Snapshot.take(primary, {"app": state},
                  storage_options={"mirror_url": mirror}, compression="zstd")
    for root in (primary, mirror):
        dst = _compressible_state(v=0.0)
        Snapshot(root).restore({"app": dst})
        np.testing.assert_array_equal(dst["w"], state["w"])


def test_compression_with_sharded_state_and_reshard(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P

    from torchsnapshot_tpu.parallel import make_mesh

    devices = jax.devices()
    if len(devices) < 4:
        pytest.skip("needs >=4 devices")
    mesh = make_mesh({"data": 2, "model": 2}, devices=devices[:4])
    arr = jnp.arange(64 * 128, dtype=jnp.float32).reshape(64, 128)
    sharded = jax.device_put(arr, NamedSharding(mesh, P("data", "model")))
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": StateDict(x=sharded)}, compression="zstd")
    codecs = _entry_codecs(root)
    assert any(c and c.startswith("zstd") for c in codecs.values())

    # restore into a DIFFERENT layout
    mesh2 = make_mesh({"data": 4, "model": 1}, devices=devices[:4])
    dst = jax.device_put(
        jnp.zeros_like(arr), NamedSharding(mesh2, P("data", None))
    )
    out = StateDict(x=dst)
    Snapshot(root).restore({"app": out})
    np.testing.assert_array_equal(np.asarray(out["x"]), np.asarray(arr))


def test_compression_with_batching_composes(tmp_path, monkeypatch):
    """Batched (byte-ranged) payloads skip compression by design; the
    snapshot as a whole still round-trips."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    state = StateDict(
        big=np.arange(300_000, dtype=np.float32),
        **{f"small_{i}": np.full((64,), float(i), np.float32) for i in range(20)},
    )
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": state}, compression="zstd")
    dst = StateDict(
        big=np.zeros(300_000, np.float32),
        **{f"small_{i}": np.zeros((64,), np.float32) for i in range(20)},
    )
    Snapshot(root).restore({"app": dst})
    np.testing.assert_array_equal(dst["big"], state["big"])
    for i in range(20):
        np.testing.assert_array_equal(dst[f"small_{i}"], state[f"small_{i}"])


def test_objects_compress(tmp_path):
    payload = {"text": "tok " * 50_000, "ids": list(range(1000))}
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": StateDict(obj=[payload])}, compression="zstd")
    codecs = _entry_codecs(root)
    assert any(c and c.startswith("zstd") for c in codecs.values())
    loaded = Snapshot(root).read_state_dict(key="app")
    assert loaded["obj"][0]["text"] == payload["text"]
    assert loaded["obj"][0]["ids"] == payload["ids"]


def test_consolidate_preserves_compression(tmp_path):
    from torchsnapshot_tpu.dedup import consolidate

    base, inc, flat = (str(tmp_path / n) for n in ("b", "i", "f"))
    state = _compressible_state()
    Snapshot.take(base, {"app": state}, record_digests=True, compression="zstd")
    state2 = _compressible_state()
    state2["w"] = state2["w"] * 2.0
    Snapshot.take(inc, {"app": state2}, incremental_base=base,
                  compression="zstd")
    consolidate(inc, flat)
    dst = _compressible_state(v=0.0)
    Snapshot(flat).restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state2["w"])
    np.testing.assert_array_equal(dst["b"], state2["b"])


def test_codec_survives_yaml_round_trip(tmp_path):
    root = str(tmp_path / "s")
    Snapshot.take(root, {"app": _compressible_state()}, compression="zlib:4")
    text = open(os.path.join(root, ".snapshot_metadata")).read()
    assert "zlib:4" in text
    meta = SnapshotMetadata.from_yaml(text)
    # uncompressed snapshots must not gain a codec key (on-disk format pin)
    root2 = str(tmp_path / "raw")
    Snapshot.take(root2, {"app": _compressible_state()})
    assert "codec" not in open(os.path.join(root2, ".snapshot_metadata")).read()


def test_replicated_codec_propagates_across_ranks():
    """Replicated entries are recorded by every rank but staged only by
    the writer; the codec must propagate to the other ranks' copies like
    checksum/digest/origin do — a non-writer restoring a compressed chunk
    without decompressing would fail (or worse)."""
    from torchsnapshot_tpu.manifest import ArrayEntry, ChunkedArrayEntry, Shard
    from torchsnapshot_tpu.snapshot import _propagate_checksums

    def make(codec, checksum):
        sub = ArrayEntry(
            location="replicated/app/w_0", serializer="buffer_protocol",
            dtype="float32", shape=[8], replicated=True,
            checksum=checksum, codec=codec,
        )
        return ChunkedArrayEntry(
            dtype="float32", shape=[8],
            chunks=[Shard(offsets=[0], sizes=[8], array=sub)],
            replicated=True,
        )

    manifest = {
        "0/app/w": make("zstd:3", "crc32c:deadbeef"),  # the writing rank
        "1/app/w": make(None, None),                   # recorded, not staged
    }
    _propagate_checksums(manifest)
    other = manifest["1/app/w"].chunks[0].array
    assert other.codec == "zstd:3"
    assert other.checksum == "crc32c:deadbeef"


def test_zstd_level_validated_up_front():
    with pytest.raises(ValueError, match="zstd level"):
        resolve_codec("zstd:99")
    with pytest.raises(ValueError, match="zstd level"):
        resolve_codec("zstd:0")


def test_zlib_decompress_honors_expected_size_bound():
    import zlib as _zlib

    data = b"A" * 1_000_000
    packed = _zlib.compress(data, 6)
    # an entry lying about its size must not allocate the full stream
    with pytest.raises(RuntimeError, match="exceeds expected|expected"):
        decompress("zlib:6", packed, expected_size=1024)


def test_zlib_trailing_garbage_rejected():
    """Bytes appended after a complete zlib stream must be rejected even
    when the stream itself decompresses to exactly expected_size — with
    checksums disabled, nothing downstream would catch the mutation."""
    import zlib as _zlib

    data = b"B" * 4096
    packed = _zlib.compress(data, 6)
    assert decompress("zlib:6", packed, expected_size=len(data)) == data
    with pytest.raises(RuntimeError, match="trailing"):
        decompress("zlib:6", packed + b"junk", expected_size=len(data))


def test_dedup_keeps_verify_coverage_for_checksumless_raw_base(tmp_path, monkeypatch):
    """Base saved with checksums disabled (raw): the deduplicated entry
    in the incremental must still get a checksum computed from the
    (identical) staged bytes, not silently lose verify coverage."""
    base, inc = str(tmp_path / "b"), str(tmp_path / "i")
    state = _compressible_state()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_CHECKSUM", "0")
    Snapshot.take(base, {"app": state}, record_digests=True)
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_CHECKSUM")
    Snapshot.take(inc, {"app": state}, incremental_base=base)

    from torchsnapshot_tpu.cli import _entry_payloads

    meta = Snapshot(inc).metadata
    checksums = [
        c
        for e in meta.manifest.values()
        for _, _, c, _, origin in _entry_payloads(e)
        if origin is not None
    ]
    assert checksums and all(c is not None for c in checksums)
    dst = _compressible_state(v=0.0)
    Snapshot(inc).restore({"app": dst})  # verification runs and passes
    np.testing.assert_array_equal(dst["w"], state["w"])


def test_diff_does_not_flag_raw_vs_compressed_as_changed(tmp_path, capsys):
    """Checksums cover stored bytes, so the same content saved raw vs
    compressed hashes differently — diff must fall through to 'unknown'
    (or use digests), never claim 'changed'."""
    from torchsnapshot_tpu.cli import main

    a, b = str(tmp_path / "a"), str(tmp_path / "b")
    state = _compressible_state()
    Snapshot.take(a, {"app": state})
    Snapshot.take(b, {"app": state}, compression="zstd")
    assert main(["diff", a, b]) == 0
    out = capsys.readouterr().out
    assert "0 changed" in out, out
    assert "indeterminate" in out, out

    # with digests recorded on both sides the verdict is decisive: same
    a2, b2 = str(tmp_path / "a2"), str(tmp_path / "b2")
    Snapshot.take(a2, {"app": state}, record_digests=True)
    Snapshot.take(b2, {"app": state}, record_digests=True, compression="zstd")
    assert main(["diff", a2, b2]) == 0
    out2 = capsys.readouterr().out
    assert "0 changed" in out2, out2
    assert "3 unchanged" in out2, out2


def test_zstd_bomb_header_rejected_before_allocation():
    zstandard = pytest.importorskip("zstandard")
    payload = compress("zstd:3", b"x" * 100_000)
    # entry lies: expected far smaller than the frame header declares
    with pytest.raises(RuntimeError, match="declares"):
        decompress("zstd:3", payload, expected_size=512)


def test_interop_export_from_compressed_snapshot(tmp_path):
    """Migrating a COMPRESSED native snapshot to the reference's on-disk
    format must transparently decompress (the reference format has no
    codec concept) — interop is unaffected by compression."""
    from torchsnapshot_tpu.tricks.torchsnapshot_interop import (
        load_torchsnapshot,
        migrate_to_torchsnapshot,
    )

    native, exported = str(tmp_path / "native"), str(tmp_path / "exported")
    state = _compressible_state()
    Snapshot.take(native, {"app": state}, compression="zstd")
    migrate_to_torchsnapshot(native, exported)

    # the export is reference-format: read it back with the black-box
    # reference reader and compare content
    loaded = load_torchsnapshot(exported)
    np.testing.assert_array_equal(np.asarray(loaded["app"]["w"]), state["w"])
    np.testing.assert_array_equal(np.asarray(loaded["app"]["b"]), state["b"])
    # no codec keys may leak into the reference-format metadata
    meta = open(os.path.join(exported, ".snapshot_metadata")).read()
    assert "codec" not in meta


def test_read_object_decompresses(tmp_path):
    root = str(tmp_path / "s")
    state = _compressible_state()
    Snapshot.take(root, {"app": state}, compression="zstd")
    w = Snapshot(root).read_object("0/app/w")
    np.testing.assert_array_equal(np.asarray(w), state["w"])
