"""Batcher tests (reference: tests/test_batcher.py — batching x chunking x
dtype matrix, plan-level fulfillment, round trips through the full stack)."""

import os

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.batcher import (
    BatchedBufferConsumer,
    batch_read_requests,
    batch_write_requests,
)
from torchsnapshot_tpu.io_types import ReadReq, WriteReq
from torchsnapshot_tpu.io_preparers.array import ArrayIOPreparer


def _prepare(arrs):
    entries, reqs = [], []
    for i, arr in enumerate(arrs):
        entry, wr = ArrayIOPreparer.prepare_write(f"0/m/p{i}", arr)
        entries.append(entry)
        reqs.extend(wr)
    return entries, reqs


def test_batch_write_packs_small_arrays() -> None:
    arrs = [np.full((10, 10), i, dtype=np.float32) for i in range(8)]
    entries, reqs = _prepare(arrs)
    entries, batched = batch_write_requests(entries, reqs)
    assert len(batched) == 1
    assert batched[0].path.startswith("batched/")
    offsets = [e.byte_range for e in entries]
    assert all(br is not None for br in offsets)
    assert offsets[0][0] == 0
    # all entries point at the same slab
    assert len({e.location for e in entries}) == 1


def test_batched_roundtrip_through_stack(tmp_path, monkeypatch) -> None:
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    arrs = {f"p{i}": np.random.default_rng(i).standard_normal((32, 32)).astype(np.float32) for i in range(6)}
    app_state = {"m": StateDict(**arrs)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), app_state)

    # all six arrays live in one slab file
    files = [
        os.path.relpath(os.path.join(dp, f), tmp_path / "snap")
        for dp, _, fs in os.walk(tmp_path / "snap")
        for f in fs
    ]
    slab_files = [f for f in files if f.startswith("batched/")]
    assert len(slab_files) == 1
    assert not any(f.startswith("0/m/") for f in files)

    dst = StateDict(**{k: np.zeros((32, 32), dtype=np.float32) for k in arrs})
    snapshot.restore({"m": dst})
    for k, v in arrs.items():
        np.testing.assert_array_equal(dst[k], v)


def test_batched_read_object(tmp_path, monkeypatch) -> None:
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    arrs = {f"p{i}": np.full((4, 4), i, dtype=np.int32) for i in range(4)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(**arrs)})
    out = snapshot.read_object("0/m/p2")
    np.testing.assert_array_equal(out, np.full((4, 4), 2, dtype=np.int32))


def test_replicated_entries_not_batched(tmp_path, monkeypatch) -> None:
    """Replicated chunk locations are deterministic across ranks and must
    not be rewritten to per-writer slab names."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    arrs = {f"p{i}": np.ones((8, 8), dtype=np.float32) for i in range(4)}
    snapshot = Snapshot.take(
        str(tmp_path / "snap"), {"m": StateDict(**arrs)}, replicated=["m/*"]
    )
    manifest = snapshot.get_manifest()
    for i in range(4):
        entry = manifest[f"0/m/p{i}"]
        assert entry.chunks[0].array.location.startswith("replicated/")


def test_batch_read_requests_merges_adjacent() -> None:
    consumed = {}

    class Rec:
        def __init__(self, key, cost):
            self.key = key
            self.cost = cost

        async def consume_buffer(self, buf, executor=None):
            consumed[self.key] = bytes(buf)

        def get_consuming_cost_bytes(self):
            return self.cost

    reqs = [
        ReadReq("f", Rec("a", 10), byte_range=(0, 10)),
        ReadReq("f", Rec("b", 10), byte_range=(10, 20)),
        ReadReq("f", Rec("c", 5), byte_range=(20, 25)),
        ReadReq("g", Rec("d", 5), byte_range=(0, 5)),
        ReadReq("h", Rec("e", 3)),  # whole-file read untouched
    ]
    merged = batch_read_requests(reqs)
    spanning = [r for r in merged if r.path == "f"]
    assert len(spanning) == 1
    assert spanning[0].byte_range == (0, 25)
    assert isinstance(spanning[0].buffer_consumer, BatchedBufferConsumer)
    assert len([r for r in merged if r.path == "g"]) == 1
    assert len([r for r in merged if r.path == "h"]) == 1


def test_batch_read_requests_respects_gap() -> None:
    class Null:
        async def consume_buffer(self, buf, executor=None):
            pass

        def get_consuming_cost_bytes(self):
            return 1

    far = 100 * 1024 * 1024
    reqs = [
        ReadReq("f", Null(), byte_range=(0, 10)),
        ReadReq("f", Null(), byte_range=(far, far + 10)),
    ]
    merged = batch_read_requests(reqs)
    assert len(merged) == 2  # gap too large to merge


@pytest.mark.parametrize("dtype", ["float32", "bfloat16", "int8"])
def test_batching_dtype_matrix(tmp_path, monkeypatch, dtype) -> None:
    from torchsnapshot_tpu.test_utils import rand_array

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    arrs = {f"p{i}": rand_array(dtype, (16, 4), seed=i) for i in range(3)}
    snapshot = Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(**arrs)})
    dst = StateDict(**{k: np.zeros_like(v) for k, v in arrs.items()})
    snapshot.restore({"m": dst})
    for k, v in arrs.items():
        assert dst[k].tobytes() == v.tobytes()
