"""Device-resident fingerprints: incremental saves without the DtoH copy.

The host dedup path (test_incremental.py) proves unchanged payloads skip
the storage WRITE; these tests prove that with ``device_digests=True``
unchanged device payloads skip the STAGING TRANSFER too — the staging
executor is never entered for them — while every mutation still restages,
and restores stay bit-exact. Fingerprint algorithm properties (bit/
permutation/length sensitivity, cross-dtype support, determinism) are
covered directly against device_digest.py.
"""

from __future__ import annotations

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.device_digest import PREFIX, device_fingerprint
from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager


@pytest.fixture
def staging_spy(monkeypatch):
    """Records the entry location of every payload that reaches the full
    staging path (DtoH + serialize + hash)."""
    staged = []
    orig = ArrayBufferStager._stage_and_sum

    def spy(self, arr):
        staged.append(self.entry.location if self.entry else "?")
        return orig(self, arr)

    monkeypatch.setattr(ArrayBufferStager, "_stage_and_sum", spy)
    return staged


# --------------------------------------------------------- fingerprint unit


def test_fingerprint_format_and_determinism():
    x = jnp.arange(1000, dtype=jnp.float32)
    fp1 = device_fingerprint(x)
    fp2 = device_fingerprint(jnp.arange(1000, dtype=jnp.float32))
    assert fp1 == fp2
    algo, hexpart = fp1.split(":")
    assert algo == PREFIX
    assert len(hexpart) == 32
    int(hexpart, 16)


def test_fingerprint_single_bit_sensitivity():
    x = jnp.zeros(4096, jnp.uint32)
    base = device_fingerprint(x)
    for pos in (0, 1, 2048, 4095):
        assert device_fingerprint(x.at[pos].set(1)) != base, pos


def test_fingerprint_permutation_sensitivity():
    x = jnp.arange(512, dtype=jnp.int32)
    y = x[::-1]
    assert device_fingerprint(x) != device_fingerprint(y)


def test_fingerprint_length_sensitivity():
    # Same word stream prefix, different lengths.
    a = jnp.zeros(16, jnp.uint32)
    b = jnp.zeros(32, jnp.uint32)
    assert device_fingerprint(a) != device_fingerprint(b)


def test_fingerprint_dtype_distinguished():
    # Identical byte count + identical zero bytes, different dtypes
    # produce different word streams only via widening; the length term
    # keeps streams of equal widened shape distinct per byte size, and
    # equal byte content with equal dtype width hashes equal.
    a = jnp.zeros(64, jnp.uint16)  # 128 bytes, words widened from u16
    b = jnp.zeros(32, jnp.uint32)  # 128 bytes, native words
    fa, fb = device_fingerprint(a), device_fingerprint(b)
    assert fa is not None and fb is not None
    # Not required to differ (both all-zero streams of equal byte length
    # could legitimately collide per construction) — but matching is
    # always additionally guarded by entry dtype/shape via the location
    # and nbytes. Just assert both computed.


@pytest.mark.parametrize(
    "dtype",
    [jnp.float32, jnp.bfloat16, jnp.float16, jnp.int8, jnp.uint8, jnp.int32, jnp.bool_],
)
def test_fingerprint_dtype_support(dtype):
    x = jnp.asarray(np.random.default_rng(0).integers(0, 2, size=257), dtype=dtype)
    fp = device_fingerprint(x)
    assert fp is not None and fp.startswith(PREFIX + ":")


def test_fingerprint_empty_and_scalar():
    assert device_fingerprint(jnp.zeros((0,), jnp.float32)) is not None
    assert device_fingerprint(jnp.asarray(1.5)) is not None


def test_fingerprint_non_jax_returns_none():
    assert device_fingerprint(np.zeros(4)) is None
    assert device_fingerprint("nope") is None


def test_fingerprint_matches_across_reshape_of_same_bytes():
    # Fingerprint is over the raveled content: same bytes, same result —
    # shape is carried by the manifest entry, mirroring how the sha256
    # content digest behaves.
    x = jnp.arange(64, dtype=jnp.float32)
    assert device_fingerprint(x) == device_fingerprint(x.reshape(8, 8))


# ------------------------------------------------------------- end to end


def test_unchanged_payloads_skip_staging(tmp_path, staging_spy):
    w = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
    b = jnp.ones((128,), jnp.bfloat16)
    state = {"m": StateDict(w=w, b=b)}
    Snapshot.take(str(tmp_path / "base"), state, device_digests=True)
    assert len(staging_spy) > 0  # base pays staging
    staging_spy.clear()

    # Fresh device buffers, same values: nothing stages.
    state2 = {"m": StateDict(w=w + 0, b=b + 0)}
    snap = Snapshot.take(
        str(tmp_path / "incr"),
        state2,
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    assert staging_spy == []

    dst = {"m": StateDict(w=jnp.zeros_like(w), b=jnp.zeros_like(b))}
    snap.restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))
    np.testing.assert_array_equal(np.asarray(dst["m"]["b"]), np.asarray(b))


def test_changed_payload_restages(tmp_path, staging_spy):
    w = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
    b = jnp.ones((128,), jnp.bfloat16)
    state = {"m": StateDict(w=w, b=b)}
    Snapshot.take(str(tmp_path / "base"), state, device_digests=True)
    staging_spy.clear()

    state2 = {"m": StateDict(w=w.at[3, 3].add(1.0), b=b)}
    snap = Snapshot.take(
        str(tmp_path / "incr"),
        state2,
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    assert len(staging_spy) == 1 and "m/w" in staging_spy[0]

    dst = {"m": StateDict(w=jnp.zeros_like(w), b=jnp.zeros_like(b))}
    snap.restore(dst)
    np.testing.assert_array_equal(
        np.asarray(dst["m"]["w"]), np.asarray(state2["m"]["w"])
    )


def test_base_without_device_digests_falls_back_to_host_dedup(tmp_path, staging_spy):
    """A base taken with only record_digests still deduplicates — via the
    staged-bytes sha256 — it just pays the DtoH."""
    w = jnp.arange(256, dtype=jnp.float32)
    state = {"m": StateDict(w=w)}
    Snapshot.take(str(tmp_path / "base"), state, record_digests=True)
    staging_spy.clear()

    snap = Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    # Staging DID run (no device fingerprint in the base to match) ...
    assert len(staging_spy) == 1
    # ... but the write was still deduplicated via sha256.
    meta = snap.metadata
    from torchsnapshot_tpu.dedup import _iter_payload_entries

    payloads = [
        p
        for e in meta.manifest.values()
        for p in _iter_payload_entries(e)
    ]
    assert payloads and all(p.origin for p in payloads)
    # And THIS take recorded fingerprints, so the next one can skip DtoH.
    assert all(p.device_digest for p in payloads)


def test_env_var_enables(tmp_path, staging_spy, monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_DEVICE_DIGESTS", "1")
    w = jnp.arange(256, dtype=jnp.float32)
    Snapshot.take(str(tmp_path / "base"), {"m": StateDict(w=w)})
    staging_spy.clear()
    Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
    )
    assert staging_spy == []


def test_sharded_array_skips_staging(tmp_path, staging_spy):
    """GSPMD-sharded arrays (the frozen-backbone case): every owned piece
    fingerprints on its device and skips staging when unchanged."""
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("x", "y"))
    sharding = NamedSharding(mesh, PartitionSpec("x", "y"))
    w = jax.device_put(
        jnp.arange(64 * 64, dtype=jnp.float32).reshape(64, 64), sharding
    )
    state = {"m": StateDict(w=w)}
    Snapshot.take(str(tmp_path / "base"), state, device_digests=True)
    assert len(staging_spy) > 0
    staging_spy.clear()

    snap = Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    assert staging_spy == []

    # Restore onto a DIFFERENT sharding: origin reads + scatter still work.
    sharding2 = NamedSharding(mesh, PartitionSpec("y", "x"))
    dst = {"m": StateDict(w=jax.device_put(jnp.zeros_like(w), sharding2))}
    snap.restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


def test_save_dtype_composes(tmp_path, staging_spy):
    """save_dtype downcasts on device BEFORE fingerprinting, so the
    fingerprint covers the bytes actually stored and unchanged downcast
    payloads skip staging across saves."""
    w = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
    sd = {"m/**": "bfloat16"}
    state = {"m": StateDict(w=w)}
    Snapshot.take(
        str(tmp_path / "base"), state, device_digests=True, save_dtype=sd
    )
    staging_spy.clear()
    snap = Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
        save_dtype=sd,
    )
    assert staging_spy == []
    dst = {"m": StateDict(w=jnp.zeros_like(w))}
    snap.restore(dst)
    np.testing.assert_array_equal(
        np.asarray(dst["m"]["w"]), np.asarray(w.astype(jnp.bfloat16).astype(jnp.float32))
    )


def test_async_take_device_dedup(tmp_path, staging_spy):
    w = jnp.arange(4096, dtype=jnp.float32)
    state = {"m": StateDict(w=w)}
    Snapshot.take(str(tmp_path / "base"), state, device_digests=True)
    staging_spy.clear()
    pending = Snapshot.async_take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    snap = pending.wait()
    assert staging_spy == []
    dst = {"m": StateDict(w=jnp.zeros_like(w))}
    snap.restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


def test_consolidate_materializes_device_deduped(tmp_path):
    """CLI consolidate resolves origin payloads of a device-deduped
    snapshot into a self-contained one."""
    from torchsnapshot_tpu.dedup import consolidate

    w = jnp.arange(1024, dtype=jnp.float32)
    Snapshot.take(str(tmp_path / "base"), {"m": StateDict(w=w)}, device_digests=True)
    Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    consolidate(str(tmp_path / "incr"), str(tmp_path / "solid"))
    dst = {"m": StateDict(w=jnp.zeros_like(w))}
    Snapshot(str(tmp_path / "solid")).restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))
    # Fingerprints survive consolidation (origins cleared): the flattened
    # snapshot still serves as a DtoH-skipping base for future takes.
    from torchsnapshot_tpu.dedup import _iter_payload_entries

    payloads = [
        p
        for e in Snapshot(str(tmp_path / "solid")).metadata.manifest.values()
        for p in _iter_payload_entries(e)
    ]
    assert payloads and all(p.device_digest and p.origin is None for p in payloads)

def test_int4_payload_falls_back_without_crashing(tmp_path, staging_spy):
    """Sub-byte packings (int4) have no elementwise uint8 bitcast — jax
    rejects them with ValueError; the take must fall back to host staging
    rather than fail."""
    try:
        x = jnp.arange(-8, 8, dtype=jnp.int4)
    except (TypeError, AttributeError):
        pytest.skip("int4 unsupported in this jax build")
    assert device_fingerprint(x) is None
    state = {"m": StateDict(q=x)}
    Snapshot.take(str(tmp_path / "base"), state, device_digests=True)
    staging_spy.clear()
    snap = Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(q=x + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
    )
    # Host path ran (staged), and sha-dedup still elided the write.
    assert len(staging_spy) == 1
    dst = {"m": StateDict(q=jnp.zeros_like(x))}
    snap.restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["q"]), np.asarray(x))


def test_checkpoint_manager_plumbs_device_digests(tmp_path, staging_spy):
    from torchsnapshot_tpu.manager import CheckpointManager

    w = jnp.arange(512, dtype=jnp.float32)
    mgr = CheckpointManager(
        str(tmp_path / "ckpts"), incremental=True, device_digests=True
    )
    mgr.save(0, {"m": StateDict(w=w)})
    staging_spy.clear()
    mgr.save(1, {"m": StateDict(w=w + 0)})  # chains against step 0
    assert staging_spy == []
    dst = {"m": StateDict(w=jnp.zeros_like(w))}
    Snapshot(mgr.path_for(1)).restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


# ------------------------------------------------- restore-side skip


@pytest.fixture
def consume_spy(monkeypatch):
    """Records every payload consume on the restore path (dense + sharded):
    a fingerprint-skipped restore consumes nothing."""
    consumed = []
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferConsumer
    from torchsnapshot_tpu.io_preparers.sharded import _ShardScatterConsumer

    for klass in (ArrayBufferConsumer, _ShardScatterConsumer):
        orig = klass._consume_sync

        def spy(self, buf, _orig=orig):
            consumed.append(type(self).__name__)
            return _orig(self, buf)

        monkeypatch.setattr(klass, "_consume_sync", spy)
    return consumed


def test_restore_skips_matching_destination(tmp_path, consume_spy):
    w = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
    b = jnp.ones((128,), jnp.bfloat16)
    Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=w, b=b)}, device_digests=True)

    # Destination already holds the content (fresh buffers, same values).
    dst = {"m": StateDict(w=w + 0, b=b + 0)}
    consume_spy.clear()
    Snapshot(str(tmp_path / "snap")).restore(dst, device_digests=True)
    assert consume_spy == []
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))

    # A stale destination still gets corrected.
    dst2 = {"m": StateDict(w=w.at[0, 0].add(7.0), b=b + 0)}
    consume_spy.clear()
    Snapshot(str(tmp_path / "snap")).restore(dst2, device_digests=True)
    assert len(consume_spy) == 1  # only w re-read
    np.testing.assert_array_equal(np.asarray(dst2["m"]["w"]), np.asarray(w))


def test_restore_skip_requires_dtype_match(tmp_path, consume_spy):
    """A dtype-differing destination must NOT skip: restore casts, so the
    destination's bytes are not the snapshot's bytes."""
    w = jnp.arange(256, dtype=jnp.bfloat16)
    Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=w)}, device_digests=True)
    dst = {"m": StateDict(w=jnp.zeros(256, jnp.float32))}
    consume_spy.clear()
    Snapshot(str(tmp_path / "snap")).restore(dst, device_digests=True)
    assert len(consume_spy) == 1
    np.testing.assert_array_equal(
        np.asarray(dst["m"]["w"]), np.asarray(w.astype(jnp.float32))
    )


def test_restore_skip_off_by_default(tmp_path, consume_spy):
    w = jnp.arange(256, dtype=jnp.float32)
    Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=w)}, device_digests=True)
    dst = {"m": StateDict(w=w + 0)}
    consume_spy.clear()
    Snapshot(str(tmp_path / "snap")).restore(dst)
    assert len(consume_spy) == 1  # no skip without the opt-in


def test_restore_skip_sharded(tmp_path, consume_spy):
    if len(jax.devices()) < 4:
        pytest.skip("needs >=4 devices")
    from jax.sharding import Mesh, NamedSharding, PartitionSpec

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("x", "y"))
    sharding = NamedSharding(mesh, PartitionSpec("x", "y"))
    w = jax.device_put(
        jnp.arange(64 * 64, dtype=jnp.float32).reshape(64, 64), sharding
    )
    Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=w)}, device_digests=True)

    # Same values on a DIFFERENT sharding: global-slice fingerprints still
    # verify, so the restore keeps the destination (and its sharding).
    sharding2 = NamedSharding(mesh, PartitionSpec("y", "x"))
    dst = {"m": StateDict(w=jax.device_put(w + 0, sharding2))}
    consume_spy.clear()
    Snapshot(str(tmp_path / "snap")).restore(dst, device_digests=True)
    assert consume_spy == []
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))
    assert dst["m"]["w"].sharding.is_equivalent_to(sharding2, 2)

    # One stale element anywhere forces a normal sharded read.
    dst2 = {"m": StateDict(w=jax.device_put(w.at[10, 10].add(1.0), sharding))}
    consume_spy.clear()
    Snapshot(str(tmp_path / "snap")).restore(dst2, device_digests=True)
    assert len(consume_spy) > 0
    np.testing.assert_array_equal(np.asarray(dst2["m"]["w"]), np.asarray(w))


def test_restore_skip_incremental_chain_reload(tmp_path, consume_spy):
    """The serving-reload story: a process holding step N's state restores
    step N+1 (incremental on N) — only the changed payload is read."""
    w = jnp.arange(2048, dtype=jnp.float32)  # frozen
    a = jnp.ones(64, jnp.float32)  # trainable
    Snapshot.take(
        str(tmp_path / "s0"), {"m": StateDict(w=w, a=a)}, device_digests=True
    )
    a1 = a * 2.0
    Snapshot.take(
        str(tmp_path / "s1"),
        {"m": StateDict(w=w + 0, a=a1)},
        incremental_base=str(tmp_path / "s0"),
        device_digests=True,
    )
    # A process still holding step 0's state reloads step 1.
    dst = {"m": StateDict(w=w + 0, a=a + 0)}
    consume_spy.clear()
    Snapshot(str(tmp_path / "s1")).restore(dst, device_digests=True)
    assert len(consume_spy) == 1  # only the adapter
    np.testing.assert_array_equal(np.asarray(dst["m"]["a"]), np.asarray(a1))
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


def test_async_restore_device_digests(tmp_path, consume_spy):
    w = jnp.arange(512, dtype=jnp.float32)
    Snapshot.take(str(tmp_path / "snap"), {"m": StateDict(w=w)}, device_digests=True)
    dst = {"m": StateDict(w=w + 0)}
    consume_spy.clear()
    pending = Snapshot(str(tmp_path / "snap")).async_restore(
        dst, device_digests=True
    )
    pending.wait()
    assert consume_spy == []
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


def test_checkpoint_manager_restore_device_digests(tmp_path, consume_spy):
    from torchsnapshot_tpu.manager import CheckpointManager

    w = jnp.arange(512, dtype=jnp.float32)
    mgr = CheckpointManager(
        str(tmp_path / "ckpts"), incremental=True, device_digests=True
    )
    mgr.save(0, {"m": StateDict(w=w)})
    dst = {"m": StateDict(w=w + 0)}
    consume_spy.clear()
    mgr.restore(dst)
    assert consume_spy == []
    np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))


def test_manager_warmup_compiles_fingerprints(tmp_path, monkeypatch):
    """warmup() with device_digests pre-dispatches the fingerprint jit for
    every array shape, so the first save pays no fingerprint compiles."""
    from torchsnapshot_tpu import device_digest
    from torchsnapshot_tpu.manager import CheckpointManager

    dispatched = []
    orig = device_digest._dispatch

    def spy(arr):
        dispatched.append(tuple(arr.shape))
        return orig(arr)

    monkeypatch.setattr(device_digest, "_dispatch", spy)
    w = jnp.arange(4096, dtype=jnp.float32).reshape(64, 64)
    b = jnp.ones((128,), jnp.bfloat16)
    mgr = CheckpointManager(
        str(tmp_path / "ckpts"), incremental=True, device_digests=True
    )
    mgr.warmup({"m": StateDict(w=w, b=b)})
    assert (64, 64) in dispatched and (128,) in dispatched


def test_compression_composes_with_device_digests(tmp_path, staging_spy, consume_spy):
    """Fingerprints cover the UNCOMPRESSED device content, so the skip
    works identically for compressed snapshots on both sides."""
    w = jnp.arange(8192, dtype=jnp.float32)  # compressible
    Snapshot.take(
        str(tmp_path / "base"),
        {"m": StateDict(w=w)},
        device_digests=True,
        compression="zstd",
    )
    staging_spy.clear()
    snap = Snapshot.take(
        str(tmp_path / "incr"),
        {"m": StateDict(w=w + 0)},
        incremental_base=str(tmp_path / "base"),
        device_digests=True,
        compression="zstd",
    )
    assert staging_spy == []  # DtoH skipped despite the codec
    consume_spy.clear()
    dst = {"m": StateDict(w=w + 0)}
    snap.restore(dst, device_digests=True)
    assert consume_spy == []  # read skipped too
    # And a cold restore still decompresses correctly.
    cold = {"m": StateDict(w=jnp.zeros_like(w))}
    snap.restore(cold)
    np.testing.assert_array_equal(np.asarray(cold["m"]["w"]), np.asarray(w))


def test_env_var_falsy_spellings(monkeypatch):
    from torchsnapshot_tpu.device_digest import enabled_by_env

    for off in ("", "0", "false"):
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_DEVICE_DIGESTS", off)
        assert not enabled_by_env(), off
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_DEVICE_DIGESTS", "1")
    assert enabled_by_env()


def test_batching_warns_for_device_digests(tmp_path, monkeypatch, caplog):
    """Batched (small) payloads can never match fingerprints; the
    existing batching/dedup warning must fire for device_digests-only
    takes too."""
    import logging

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_ENABLE_BATCHING", "1")
    w = jnp.arange(64, dtype=jnp.float32)
    with caplog.at_level(logging.WARNING, logger="torchsnapshot_tpu.snapshot"):
        Snapshot.take(str(tmp_path / "s"), {"m": StateDict(w=w)}, device_digests=True)
    assert any("batching" in r.message.lower() for r in caplog.records)


# ------------------------------------------------- windowed verification


def test_fingerprints_match_windowed_correctness():
    """fingerprints_match verifies in bounded windows with early exit:
    after a mismatch, thunks in later windows never materialize (so a
    failed verification also never duplicates the array's footprint)."""
    from torchsnapshot_tpu.device_digest import fingerprints_match

    arrs = [jnp.full((64,), i, jnp.float32) for i in range(10)]
    fps = [device_fingerprint(a) for a in arrs]

    calls = []

    def items(bad_at=None):
        out = []
        for i, (a, fp) in enumerate(zip(arrs, fps)):
            want = "xxh4x32:" + "0" * 32 if i == bad_at else fp
            out.append(
                (a.nbytes, lambda i=i, a=a: (calls.append(i), a)[1], want)
            )
        return out

    calls.clear()
    assert fingerprints_match(items(), window=3)
    assert calls == list(range(10))  # all verified, in order

    # Mismatch in the first window: later windows never materialize.
    calls.clear()
    assert not fingerprints_match(items(bad_at=1), window=3)
    assert max(calls) <= 2  # only the first window's slices were touched

    # An unfingerprintable slice (numpy, not jax) also fails closed.
    assert not fingerprints_match(
        [(16, lambda: np.zeros(4), "xxh4x32:" + "0" * 32)]
    )

    # Empty iterable is vacuously True (callers guard non-emptiness).
    assert fingerprints_match([])


def test_restore_skip_chunked_many_windows(tmp_path, consume_spy):
    """A chunked array with more chunks than the verification window
    still skips fully (windowed dispatch covers every chunk), and a
    mutation in the LAST chunk still forces a re-read."""
    from torchsnapshot_tpu.io_preparers import chunked

    old = chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES
    chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = 1024  # 4 rows of 64 floats
    try:
        w = jnp.arange(40 * 64, dtype=jnp.float32).reshape(40, 64)  # 10 chunks
        Snapshot.take(
            str(tmp_path / "snap"), {"m": StateDict(w=w)}, device_digests=True
        )
        meta = Snapshot(str(tmp_path / "snap")).get_manifest()
        assert any("chunk" in type(e).__name__.lower() for e in meta.values())

        dst = {"m": StateDict(w=w + 0)}
        consume_spy.clear()
        Snapshot(str(tmp_path / "snap")).restore(dst, device_digests=True)
        assert consume_spy == []
        np.testing.assert_array_equal(np.asarray(dst["m"]["w"]), np.asarray(w))

        dst2 = {"m": StateDict(w=w.at[39, 63].add(1.0))}
        consume_spy.clear()
        Snapshot(str(tmp_path / "snap")).restore(dst2, device_digests=True)
        assert len(consume_spy) > 0
        np.testing.assert_array_equal(np.asarray(dst2["m"]["w"]), np.asarray(w))
    finally:
        chunked.DEFAULT_MAX_CHUNK_SIZE_BYTES = old


def test_device_dedup_none_checksum_warns_once(tmp_path, monkeypatch, caplog):
    """A device-dedup match against a base saved with checksums disabled
    inherits checksum=None; the narrowed verification coverage is flagged
    once (advisor r4: io_preparers/array.py)."""
    import logging

    from torchsnapshot_tpu.io_preparers import array as array_mod

    w = jnp.arange(1024, dtype=jnp.float32)
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_CHECKSUM", "0")
    Snapshot.take(str(tmp_path / "base"), {"m": StateDict(w=w)}, device_digests=True)
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_CHECKSUM")

    monkeypatch.setattr(array_mod, "_warned_none_checksum", False)
    with caplog.at_level(
        logging.WARNING, logger="torchsnapshot_tpu.io_preparers.array"
    ):
        Snapshot.take(
            str(tmp_path / "incr"),
            {"m": StateDict(w=w)},
            device_digests=True,
            incremental_base=str(tmp_path / "base"),
            record_digests=True,
        )
    warnings = [r for r in caplog.records if "checksum" in r.message.lower()]
    assert len(warnings) == 1
    # Second deduped save: already warned, stays quiet.
    caplog.clear()
    with caplog.at_level(
        logging.WARNING, logger="torchsnapshot_tpu.io_preparers.array"
    ):
        Snapshot.take(
            str(tmp_path / "incr2"),
            {"m": StateDict(w=w)},
            device_digests=True,
            incremental_base=str(tmp_path / "incr"),
            record_digests=True,
        )
    assert not [r for r in caplog.records if "checksum" in r.message.lower()]


def test_fingerprints_match_byte_budget():
    """The window also closes on a BYTE budget: sharded pieces have no
    512 MB cap, so a count-only window could hold an array's whole
    footprint in slice copies. An over-budget slice goes alone; a slice
    that overflows a non-empty window is carried to the next one —
    WITHOUT being materialized twice (sizes come from the manifest, so
    the budget check precedes the slice thunk)."""
    from torchsnapshot_tpu.device_digest import fingerprints_match

    arrs = [jnp.full((256,), i, jnp.float32) for i in range(6)]  # 1 KB each
    fps = [device_fingerprint(a) for a in arrs]
    live = []

    def items():
        return [
            (a.nbytes, lambda i=i, a=a: (live.append(i), a)[1], fp)
            for i, (a, fp) in enumerate(zip(arrs, fps))
        ]

    # Budget of ~1.5 slices: every window carries its second slice over;
    # each slice is materialized EXACTLY once and all still verify.
    live.clear()
    assert fingerprints_match(items(), window=4, window_bytes=1536)
    assert live == list(range(6))

    # Budget smaller than one slice: each goes alone, still verifies.
    live.clear()
    assert fingerprints_match(items(), window=4, window_bytes=16)
    assert live == list(range(6))

    # Mismatch under byte-budgeting still fails.
    bad = items()
    bad[5] = (bad[5][0], bad[5][1], "xxh4x32:" + "0" * 32)
    assert not fingerprints_match(bad, window=4, window_bytes=1536)

    with pytest.raises(ValueError):
        fingerprints_match(items(), window=0)
    with pytest.raises(ValueError):
        fingerprints_match(items(), window_bytes=0)


def test_partial_lane_additivity_matches_full_fingerprint():
    """Fingerprint lanes are additive over any disjoint region partition
    of a piece (the property distributed verification relies on): the
    wrapping sum of partial lanes — each region tagged with its absolute
    offsets — plus the length fold equals device_fingerprint of the
    whole piece. Covers 1-word dtypes, zero-extended narrow dtypes,
    bool, scalars, and single-element partitions."""
    from torchsnapshot_tpu.device_digest import (
        combine_partials,
        partial_dispatch,
        partial_fetch,
    )

    rng = np.random.default_rng(7)
    for dtype in (jnp.float32, jnp.bfloat16, jnp.int8, jnp.bool_):
        piece = jnp.asarray(rng.standard_normal((12, 20)) * 10).astype(dtype)
        full = device_fingerprint(piece)
        assert full is not None
        groups = []
        for r0, r1 in [(0, 5), (5, 12)]:
            for c0, c1 in [(0, 7), (7, 13), (13, 20)]:
                p = partial_dispatch(piece[r0:r1, c0:c1], (12, 20), (r0, c0))
                groups.append(partial_fetch(p))
        nbytes = piece.dtype.itemsize * piece.size
        assert combine_partials(groups, nbytes) == full, dtype

    # Scalar piece: empty offsets, one region.
    sc = jnp.asarray(3.25, jnp.float32)
    p = partial_dispatch(sc, (), ())
    assert combine_partials([partial_fetch(p)], 4) == device_fingerprint(sc)

    # Degenerate single-element partition stresses the tag indexing.
    piece = jnp.arange(6, dtype=jnp.float32).reshape(2, 3)
    groups = [
        partial_fetch(
            partial_dispatch(piece[i : i + 1, j : j + 1], (2, 3), (i, j))
        )
        for i in range(2)
        for j in range(3)
    ]
    assert combine_partials(groups, 24) == device_fingerprint(piece)

    # A mutated region changes the sum (and so the verdict).
    mutated = piece.at[1, 2].add(1.0)
    groups_m = [
        partial_fetch(
            partial_dispatch(mutated[i : i + 1, j : j + 1], (2, 3), (i, j))
        )
        for i in range(2)
        for j in range(3)
    ]
    assert combine_partials(groups_m, 24) != device_fingerprint(piece)
