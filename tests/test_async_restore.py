"""async_restore: background restore overlapping caller work.

No reference analogue (its restore is synchronous only); mirrors the
fault-injection style of tests/test_async_take.py.
"""

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict


def _state(v=1.0):
    return StateDict(
        w=np.full((128, 64), v, np.float32),
        nested={"b": np.full((32,), v * 2, np.float32)},
        step=int(v),
    )


def test_async_restore_roundtrip(tmp_path):
    p = str(tmp_path / "snap")
    Snapshot.take(p, {"app": _state(3.0)})

    dst = _state(0.0)
    pending = Snapshot(p).async_restore({"app": dst})
    # caller-side work overlapping the restore (stand-in for jit compile)
    import jax
    import jax.numpy as jnp

    fn = jax.jit(lambda x: (x * 2).sum()).lower(
        jnp.zeros((8, 8), jnp.float32)
    ).compile()
    pending.wait()
    assert pending.done()
    np.testing.assert_array_equal(dst["w"], np.full((128, 64), 3.0, np.float32))
    np.testing.assert_array_equal(dst["nested"]["b"], np.full((32,), 6.0, np.float32))
    assert dst["step"] == 3
    assert float(fn(jnp.ones((8, 8), jnp.float32))) == 128.0


def test_async_restore_propagates_failure(tmp_path):
    p = str(tmp_path / "snap")
    Snapshot.take(p, {"app": _state(1.0)})
    # destination whose structure mismatches -> restore must fail via wait()
    dst = StateDict(w=np.zeros((7, 7), np.float32))
    pending = Snapshot(p).async_restore({"app": dst})
    with pytest.raises(RuntimeError):
        pending.wait()
    assert pending.done()


def test_async_restore_jax_sharded_dst(tmp_path):
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(2, 2), ("dp", "tp"))
    sharding = NamedSharding(mesh, P("dp", "tp"))
    src = jax.device_put(jnp.arange(64, dtype=jnp.float32).reshape(8, 8), sharding)
    p = str(tmp_path / "snap")
    Snapshot.take(p, {"m": StateDict(emb=src)})

    dst = StateDict(emb=jax.device_put(jnp.zeros((8, 8), jnp.float32), sharding))
    pending = Snapshot(p).async_restore({"m": dst})
    pending.wait()
    np.testing.assert_array_equal(
        np.asarray(dst["emb"]), np.arange(64, dtype=np.float32).reshape(8, 8)
    )
    assert dst["emb"].sharding.is_equivalent_to(sharding, 2)


def _async_restore_worker(rank, world_size, snap_path):
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    state = {
        "model": StateDict(w=np.arange(256, dtype=np.float32)),
        "local": StateDict(r=np.full((4,), rank, np.int32)),
    }
    Snapshot.take(snap_path, state, replicated=["model/*"])

    dst = {
        "model": StateDict(w=np.zeros(256, np.float32)),
        "local": StateDict(r=np.zeros((4,), np.int32)),
    }
    pending = Snapshot(snap_path).async_restore(dst)
    pending.wait()
    np.testing.assert_array_equal(dst["model"]["w"], np.arange(256, dtype=np.float32))
    np.testing.assert_array_equal(dst["local"]["r"], np.full((4,), rank, np.int32))
    return "ok"


@pytest.mark.multiprocess
def test_async_restore_multiprocess(tmp_path):
    from torchsnapshot_tpu.test_utils import run_with_subprocesses

    results = run_with_subprocesses(
        _async_restore_worker, 2, str(tmp_path / "snap")
    )
    assert all(v == "ok" for v in results.values())
