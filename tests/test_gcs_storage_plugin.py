"""GCS plugin logic tests against an in-memory fake bucket.

The reference gates its GCS tests on a real bucket + env var
(tests/test_gcs_storage_plugin.py:29-87); that covers Google's SDK more
than the plugin. These tests target OUR logic — chunking, rewind-on-retry,
transient classification, and the collective retry strategy — with fakes,
so they run unconditionally (test strategy: SURVEY.md §4.4 fault injection
via plugin-level fakes).
"""

from __future__ import annotations

import asyncio

import pytest

from torchsnapshot_tpu.io_types import ReadIO, WriteIO
from torchsnapshot_tpu.storage_plugins.gcs import (
    CollectiveRetryStrategy,
    GCSStoragePlugin,
)


class FakeBlob:
    def __init__(self, store: dict, name: str, fail_times: int = 0):
        self.store = store
        self.name = name
        self.chunk_size = None
        self._fail_times = fail_times
        self.upload_attempts = 0
        self.download_calls = []

    def _maybe_fail(self):
        if self._fail_times > 0:
            self._fail_times -= 1
            raise ConnectionError("fake transient")

    def upload_from_file(self, stream, size):
        self.upload_attempts += 1
        # Consume part of the stream BEFORE failing, so a retry without
        # rewind would upload a short/corrupt body.
        data = stream.read(size)
        self._maybe_fail()
        assert len(data) == size, "stream not rewound before retry"
        self.store[self.name] = bytes(data)

    def download_as_bytes(self, start=0, end=None):
        self._maybe_fail()
        self.download_calls.append((start, end))
        data = self.store[self.name]
        hi = len(data) if end is None else end + 1  # GCS end is inclusive
        return data[start:hi]

    def reload(self):
        self._maybe_fail()

    @property
    def size(self):
        return len(self.store[self.name])

    def delete(self):
        self._maybe_fail()
        del self.store[self.name]


class FakeBucket:
    def __init__(self, fail_times: int = 0):
        self.store: dict = {}
        self.blobs: dict = {}
        self.fail_times = fail_times

    def blob(self, name: str) -> FakeBlob:
        if name not in self.blobs:
            self.blobs[name] = FakeBlob(self.store, name, self.fail_times)
        return self.blobs[name]


def make_plugin(bucket: FakeBucket, **options) -> GCSStoragePlugin:
    return GCSStoragePlugin(
        "fake-bucket/prefix", storage_options={"bucket": bucket, **options}
    )


def run(coro):
    return asyncio.new_event_loop().run_until_complete(coro)


def test_write_read_roundtrip_small() -> None:
    bucket = FakeBucket()
    plugin = make_plugin(bucket)
    payload = b"hello gcs" * 100
    run(plugin.write(WriteIO(path="a/b", buf=memoryview(payload))))
    assert bucket.store["prefix/a/b"] == payload
    read_io = ReadIO(path="a/b")
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload


def test_full_read_is_single_get() -> None:
    """No-range reads go out as one streamed GET — no metadata round-trip."""
    bucket = FakeBucket()
    plugin = make_plugin(bucket, chunk_size_bytes=1000)
    payload = bytes(range(256)) * 20  # 5120 bytes
    run(plugin.write(WriteIO(path="big", buf=memoryview(payload))))
    read_io = ReadIO(path="big")
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload
    assert len(bucket.blob("prefix/big").download_calls) == 1


def test_ranged_read_chunked() -> None:
    bucket = FakeBucket()
    plugin = make_plugin(bucket, chunk_size_bytes=512)
    payload = bytes([i % 251 for i in range(4096)])
    run(plugin.write(WriteIO(path="r", buf=memoryview(payload))))
    read_io = ReadIO(path="r", byte_range=(100, 2100))
    run(plugin.read(read_io))
    assert bytes(read_io.buf) == payload[100:2100]
    blob = bucket.blob("prefix/r")
    # 2000 bytes in 512-byte chunks -> 4 end-inclusive ranged GETs.
    assert len(blob.download_calls) == 4
    assert all(e - s + 1 <= 512 for s, e in blob.download_calls)


def test_long_inflight_op_still_gets_a_retry() -> None:
    """An attempt that STARTED before the shared deadline lapsed retries
    even if it ran past the deadline — in-flight time is not a stall."""
    now = [0.0]
    slept = []

    async def fake_sleep(s):
        slept.append(s)
        now[0] += s

    strat = CollectiveRetryStrategy(
        stall_timeout_s=10.0, base_backoff_s=0.5, clock=lambda: now[0],
        sleep=fake_sleep,
    )

    async def scenario():
        strat.report_progress()  # deadline = 10
        started = now[0]  # op starts immediately
        now[0] = 300.0  # ...but runs for 300s before failing
        await strat.backoff_or_raise(ConnectionError("late"), 0, op_started_at=started)
        # Second attempt starts after the lapsed deadline and fails -> raise.
        started2 = now[0]
        with pytest.raises(ConnectionError):
            await strat.backoff_or_raise(
                ConnectionError("still down"), 1, op_started_at=started2
            )

    run(scenario())
    assert len(slept) == 1


def test_upload_rewinds_on_retry() -> None:
    bucket = FakeBucket(fail_times=2)
    plugin = make_plugin(
        bucket,
        retry_strategy=CollectiveRetryStrategy(
            base_backoff_s=0.001, sleep=asyncio.sleep
        ),
    )
    payload = b"x" * 5000
    run(plugin.write(WriteIO(path="w", buf=memoryview(payload))))
    blob = bucket.blob("prefix/w")
    assert blob.upload_attempts == 3  # two transient failures, then success
    assert bucket.store["prefix/w"] == payload


def test_resumable_chunk_size_set_for_large_uploads() -> None:
    bucket = FakeBucket()
    plugin = make_plugin(bucket, chunk_size_bytes=1024)
    run(plugin.write(WriteIO(path="big", buf=memoryview(b"y" * 4096))))
    assert bucket.blob("prefix/big").chunk_size == 1024
    # Small uploads stay single-shot.
    run(plugin.write(WriteIO(path="small", buf=memoryview(b"z" * 10))))
    assert bucket.blob("prefix/small").chunk_size is None


def test_non_transient_error_propagates_immediately() -> None:
    class Boom(Exception):
        pass

    class BadBlob(FakeBlob):
        def upload_from_file(self, stream, size):
            self.upload_attempts += 1
            raise Boom("permanent")

    bucket = FakeBucket()
    bucket.blobs["prefix/p"] = BadBlob(bucket.store, "prefix/p")
    plugin = make_plugin(bucket)
    with pytest.raises(Boom):
        run(plugin.write(WriteIO(path="p", buf=memoryview(b"data"))))
    assert bucket.blobs["prefix/p"].upload_attempts == 1


def test_collective_deadline_fails_stalled_fleet() -> None:
    now = [0.0]
    sleeps = []

    async def fake_sleep(s):
        sleeps.append(s)
        now[0] += s

    strat = CollectiveRetryStrategy(
        stall_timeout_s=10.0, base_backoff_s=1.0, clock=lambda: now[0],
        sleep=fake_sleep,
    )

    async def stalled():
        exc = ConnectionError("down")
        for attempt in range(100):
            await strat.backoff_or_raise(exc, attempt)

    with pytest.raises(ConnectionError):
        run(stalled())
    # Backoffs were attempted until the shared deadline lapsed, not 100x.
    assert 1 <= len(sleeps) < 100
    assert sum(sleeps) > 10.0


def test_first_error_after_long_idle_still_retries() -> None:
    """The stall deadline arms at first use, not construction — idle time
    before the first transfer must not consume the retry budget."""
    now = [0.0]
    slept = []

    async def fake_sleep(s):
        slept.append(s)
        now[0] += s

    strat = CollectiveRetryStrategy(
        stall_timeout_s=10.0, base_backoff_s=0.5, clock=lambda: now[0],
        sleep=fake_sleep,
    )
    now[0] = 1000.0  # long idle after construction

    async def first_failure():
        await strat.backoff_or_raise(ConnectionError("first"), 0)

    run(first_failure())  # must sleep-and-allow-retry, not raise
    assert len(slept) == 1


def test_progress_extends_collective_deadline() -> None:
    now = [0.0]

    async def fake_sleep(s):
        now[0] += s

    strat = CollectiveRetryStrategy(
        stall_timeout_s=10.0, base_backoff_s=4.0, clock=lambda: now[0],
        sleep=fake_sleep,
    )

    async def scenario():
        exc = ConnectionError("slow")
        for attempt in range(6):
            # Some OTHER coroutine in the fleet keeps making progress.
            strat.report_progress()
            await strat.backoff_or_raise(exc, attempt)
        return True

    # > 10s of cumulative backoff, but the refreshed deadline never lapses.
    assert run(scenario())


def test_end_to_end_snapshot_on_fake_gcs(tmp_path, monkeypatch) -> None:
    """Snapshot.take/restore against gs:// resolved to the fake bucket."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.storage_plugins import gcs as gcs_mod

    bucket = FakeBucket()
    monkeypatch.setattr(
        gcs_mod.GCSStoragePlugin,
        "_make_bucket",
        staticmethod(lambda name, options: bucket),
    )
    state = StateDict(arr=np.arange(100, dtype=np.float32), n=7)
    Snapshot.take("gs://bkt/snapshots/s1", {"app": state})
    dst = StateDict(arr=np.zeros(100, dtype=np.float32), n=0)
    Snapshot("gs://bkt/snapshots/s1").restore({"app": dst})
    np.testing.assert_array_equal(dst["arr"], state["arr"])
    assert dst["n"] == 7
