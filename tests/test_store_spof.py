"""Coordination-plane SPOF drill: the store-hosting process dies mid-take.

The KV store lives in rank 0's process (the same single point of failure
as the reference's rank-0-hosted TCPStore, dist_store.py:53-88). This
drill proves the failure story end to end in a REAL multi-process world:

1. the world commits a snapshot normally;
2. a second take starts and rank 0 (the store host) is SIGKILLed mid-
   staging — every surviving rank's take must raise within SECONDS (the
   client-side connection-loss detection of dist_store.TCPStore), naming
   the coordination store, instead of blocking out the 1800 s barrier
   timeout;
3. nothing is committed for the doomed take (metadata-last protocol);
4. a FRESH world — at a different world size, with a new store — restores
   the last committed snapshot and sees the exact saved content.

The drill runs over the snapshot library's OWN process group (KV-store
collectives via pg_wrapper — what the launcher's workers already join)
WITHOUT jax.distributed: jax's coordination service is rank-0-hosted
too and F-aborts surviving processes on leader death, which would mask
the behavior under test. The snapshot coordination plane is independent
of the XLA runtime by design (SURVEY §5.8), so its failure story must
hold on its own.

Recovery recipe documented in docs/source/elasticity.rst
("Coordination-plane failure").
"""

from __future__ import annotations

import os
import signal
import time

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]

SHAPE = (6, 8)


def _data(rank: int = 0) -> np.ndarray:
    return np.arange(48, dtype=np.float32).reshape(SHAPE) + rank


def _spof_worker(rank, world_size, committed_root, doomed_root):
    """Phase 1: commit a snapshot. Phase 2: take again; rank 0 (the store
    host) SIGKILLs itself mid-staging; survivors must abort fast."""
    import jax

    jax.config.update("jax_platforms", "cpu")  # sitecustomize may aim at TPU
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.dist_store import StoreConnectionLostError

    app = {
        "m": StateDict(
            emb=jnp.asarray(_data(rank)),  # per-rank device state
            host=_data(),  # replicated host state
        )
    }
    Snapshot.take(committed_root, app, replicated=["m/host"])

    if rank == 0:
        from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager

        orig = ArrayBufferStager._stage_and_sum

        def die_mid_staging(self, a):
            # Let peers finish their own staging and reach the blocking
            # manifest gather first, then die without cleanup — the
            # store server dies with this process.
            time.sleep(2.0)
            os.kill(os.getpid(), signal.SIGKILL)
            return orig(self, a)  # pragma: no cover

        ArrayBufferStager._stage_and_sum = die_mid_staging

    t0 = time.monotonic()
    try:
        Snapshot.take(
            doomed_root,
            {"m": StateDict(emb=jnp.asarray(_data(rank)) + 1, host=_data())},
            replicated=["m/host"],
        )
    except BaseException as e:  # noqa: B036
        elapsed = time.monotonic() - t0
        # The connection-loss error must be the cause (directly or
        # chained) and must name the coordination store.
        chain, cur, seen = [], e, set()
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            chain.append(cur)
            cur = cur.__cause__ or cur.__context__
        assert any(
            isinstance(c, StoreConnectionLostError) for c in chain
        ), f"rank {rank}: {type(e).__name__}: {e}"
        assert any("coordination store" in str(c) for c in chain)
        return ("aborted", elapsed)
    return ("NOT-ABORTED", time.monotonic() - t0)


def _recovery_worker(rank, world_size, committed_root):
    """A fresh, SMALLER world (new store, changed world size) restores
    the committed snapshot: replicated entries are available to every
    rank, per-rank entries to their original owner (elasticity rules)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict

    dst = StateDict(
        emb=jnp.zeros(SHAPE, jnp.float32),
        host=np.zeros(SHAPE, np.float32),
    )
    Snapshot(committed_root).restore({"m": dst})
    np.testing.assert_array_equal(dst["host"], _data())
    np.testing.assert_array_equal(np.asarray(dst["emb"]), _data(rank))
    return "ok"


def _wait_any_worker(rank, world_size):
    """Rank 0 (the store host) SIGKILLs itself while peers are blocked
    in a long-timeout wait_any; survivors must raise within seconds."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    from torchsnapshot_tpu.dist_store import StoreConnectionLostError
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    store = get_default_pg().store
    store.add("armed", 1)  # everyone reaches the store first
    store.get("armed")  # (value irrelevant; one warm round trip each)
    if rank == 0:
        time.sleep(1.5)  # let peers block in wait_any server-side
        os.kill(os.getpid(), signal.SIGKILL)
    t0 = time.monotonic()
    try:
        store.wait_any(["never-set"], timeout=600.0)
    except StoreConnectionLostError:
        return ("aborted", time.monotonic() - t0)
    return ("NOT-ABORTED", time.monotonic() - t0)


def test_leader_death_mid_wait_any_no_replicas_bounded() -> None:
    """Satellite regression guard: with ZERO replicas configured, leader
    death under a blocked wait_any fails every survivor in seconds (the
    PR 5 detection behavior is the non-replicated fallback path)."""
    results = run_with_subprocesses(
        _wait_any_worker, 3, timeout=120.0, expect_dead=(0,)
    )
    assert set(results) == {1, 2}, results
    for rank, (status, elapsed) in results.items():
        assert status == "aborted", results
        assert elapsed < 60.0, f"rank {rank} took {elapsed:.1f}s"


def _commit_barrier_worker(rank, world_size, root):
    """Phase 1 commits ``prev``. Phase 2: rank 0 — the store host — is
    SIGKILLed at the exact metadata commit point, leaving peers parked
    in the two-phase commit barrier."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp

    from torchsnapshot_tpu import Snapshot, StateDict, faultinject
    from torchsnapshot_tpu.dist_store import StoreConnectionLostError

    state = {"m": StateDict(emb=jnp.asarray(_data(rank)))}
    Snapshot.take(os.path.join(root, "prev"), state)
    if rank == 0:
        faultinject.configure("commit.metadata@1=kill")
    t0 = time.monotonic()
    try:
        Snapshot.take(
            os.path.join(root, "doomed"),
            {"m": StateDict(emb=jnp.asarray(_data(rank)) + 1)},
        )
    except BaseException as e:  # noqa: B036
        chain, cur, seen = [], e, set()
        while cur is not None and id(cur) not in seen:
            seen.add(id(cur))
            chain.append(cur)
            cur = cur.__cause__ or cur.__context__
        assert any(
            isinstance(c, StoreConnectionLostError) for c in chain
        ), f"rank {rank}: {type(e).__name__}: {e}"
        return ("aborted", time.monotonic() - t0)
    return ("NOT-ABORTED", time.monotonic() - t0)


def test_leader_death_mid_commit_barrier_no_replicas_bounded(tmp_path) -> None:
    """The kill-during-commit-barrier schedule with no replicas: the
    world must end prev-restorable + fsck-clean within the bounded
    deadline — never a 1800 s hang and never a torn commit."""
    from torchsnapshot_tpu.cli import run_fsck

    results = run_with_subprocesses(
        _commit_barrier_worker, 2, str(tmp_path), timeout=180.0,
        expect_dead=(0,),
    )
    assert set(results) == {1}, results
    status, elapsed = results[1]
    assert status == "aborted", results
    assert elapsed < 60.0, f"survivor took {elapsed:.1f}s to abort"
    # The doomed take committed nothing (the kill landed AT the commit
    # point, before the metadata write); prev is intact and fsck-clean.
    assert not os.path.exists(
        os.path.join(tmp_path, "doomed", ".snapshot_metadata")
    )
    prev = os.path.join(str(tmp_path), "prev")
    assert run_fsck(prev, echo=lambda *a, **k: None)[0] == 0
    import jax.numpy as jnp  # noqa: F401 - jax configured by conftest

    import numpy as _np

    from torchsnapshot_tpu import Snapshot, StateDict

    # The parent restores as rank 0 of a world-1 group: it sees rank 0's
    # per-rank entry from the committed prev snapshot.
    dst = {"m": StateDict(emb=_np.zeros(SHAPE, _np.float32))}
    Snapshot(prev).restore(dst)
    _np.testing.assert_array_equal(_np.asarray(dst["m"]["emb"]), _data(0))


def test_store_host_death_aborts_fast_and_world_recovers(tmp_path) -> None:
    committed = str(tmp_path / "committed")
    doomed = str(tmp_path / "doomed")

    results = run_with_subprocesses(
        _spof_worker,
        3,
        committed,
        doomed,
        timeout=240.0,
        expect_dead=(0,),
    )
    # Rank 0 died (no result); both survivors aborted, in seconds.
    assert set(results) == {1, 2}, results
    for rank, (status, elapsed) in results.items():
        assert status == "aborted", results
        assert elapsed < 60.0, f"rank {rank} took {elapsed:.1f}s to abort"

    # The doomed take committed nothing; the earlier snapshot is intact.
    assert not os.path.exists(os.path.join(doomed, ".snapshot_metadata"))
    assert os.path.isfile(os.path.join(committed, ".snapshot_metadata"))

    # A fresh 2-process world (new store, changed world size) restores
    # the committed snapshot.
    results = run_with_subprocesses(
        _recovery_worker, 2, committed, timeout=240.0
    )
    assert all(v == "ok" for v in results.values())
