"""Regenerate tests/data/reference_snapshot with the reference library.

Run on a machine where the reference (pytorch/torchsnapshot) is importable:

    python tests/data/gen_reference_snapshot.py [/path/to/reference]

The fixture pins the reference's on-disk format (YAML manifest + payload
files) so tests/test_torchsnapshot_interop.py can verify the migration
reader without the reference installed. Keep the state tiny — the fixture
is committed.
"""

import os
import shutil
import sys

HERE = os.path.dirname(os.path.abspath(__file__))
OUT = os.path.join(HERE, "reference_snapshot")


def main() -> None:
    ref = sys.argv[1] if len(sys.argv) > 1 else "/root/reference"
    sys.path.insert(0, ref)
    import torch
    import torchsnapshot
    from torchsnapshot import Snapshot, StateDict

    # Force multi-chunk output so chunk reassembly is pinned (the default
    # chunk size is bound at function-definition time, so wrap the method).
    prep = torchsnapshot.io_preparer.ChunkedTensorIOPreparer
    orig = prep.chunk_tensor
    prep.chunk_tensor = staticmethod(
        lambda tensor, chunking_dim=0, chunk_sz_bytes=64: orig(tensor, chunking_dim, 64)
    )

    torch.manual_seed(0)
    sd = StateDict(
        step=7,
        lr=0.125,
        done=False,
        name="run/alpha",  # exercises %-escaping of '/' in keys? (value only)
        blob=b"\x00\x01\xff",
        weights=torch.arange(48, dtype=torch.float32).reshape(6, 8),  # 3 chunks
        bf=torch.arange(6, dtype=torch.float32).to(torch.bfloat16),
        nested={
            "a": [torch.full((2,), 3.0), "mid", 11],
            "b": {"c": torch.arange(5, dtype=torch.int64)},
            "esc/key": torch.ones(2, dtype=torch.int8),
        },
        opt=dict(momenta=(0.9, 0.999), eps=1e-8),
    )
    if os.path.exists(OUT):
        shutil.rmtree(OUT)
    Snapshot.take(path=OUT, app_state={"app": sd})
    print("wrote", OUT)


if __name__ == "__main__":
    main()
