"""Checkpoint history (telemetry/history.py): the crash-safe journal,
p50 regression detection, the ``stats --trend`` gate, and the
OpenMetrics export."""

from __future__ import annotations

import json
import os
import re
import time

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu.cli import main
from torchsnapshot_tpu.telemetry import history
from torchsnapshot_tpu.telemetry.export import render_openmetrics


def _seed(root, walls, gbps=None):
    for i, w in enumerate(walls):
        rec = {"ts": time.time(), "op": "take", "snapshot": f"step_{i:010d}",
               "world_size": 2, "wall_s": w}
        if gbps is not None:
            rec["write_gbps"] = gbps[i]
        assert history.append_record(str(root), rec)


# ----------------------------------------------------------- journal


def test_append_is_one_line_and_reader_skips_torn_lines(tmp_path):
    _seed(tmp_path, [1.0, 1.1])
    path = history.history_path(str(tmp_path))
    with open(path, "a") as f:
        f.write('{"ts": 1, "op": "take", "wall_s": 1.2')  # torn: no newline
    records = history.load_history(str(tmp_path))
    assert [r["wall_s"] for r in records] == [1.0, 1.1]
    # The journal accepts appends after a torn tail (O_APPEND line model).
    assert history.append_record(
        str(tmp_path), {"ts": 2, "op": "take", "wall_s": 1.3}
    )
    # The torn fragment merges with the next line — exactly one record
    # is lost, never the journal.
    records = history.load_history(str(tmp_path))
    assert records[0]["wall_s"] == 1.0


def test_append_refuses_missing_root(tmp_path):
    assert not history.append_record(str(tmp_path / "nope"), {"wall_s": 1})


def test_committed_take_appends_history(tmp_path):
    """Every committed take appends a record to the snapshot ROOT —
    with the telemetry bus OFF (the default): wall time and identity
    always record."""
    state = {"model": StateDict(w=np.arange(10_000, dtype=np.float32))}
    Snapshot.take(str(tmp_path / "step_0000000001"), state)
    Snapshot.take(str(tmp_path / "step_0000000002"), state)
    records = history.load_history(str(tmp_path))
    assert len(records) == 2
    assert records[0]["snapshot"] == "step_0000000001"
    assert records[1]["snapshot"] == "step_0000000002"
    assert all(r["wall_s"] > 0 for r in records)
    assert all(r["op"] == "take" for r in records)


def test_aborted_take_appends_nothing(tmp_path):
    from torchsnapshot_tpu import faultinject

    state = {"model": StateDict(w=np.arange(10_000, dtype=np.float32))}
    faultinject.configure("fs.write@1=permanent")
    try:
        with pytest.raises(OSError):
            Snapshot.take(str(tmp_path / "step_0000000001"), state)
    finally:
        faultinject.disable()
    assert history.load_history(str(tmp_path)) == []


def test_manager_history_carries_step(tmp_path):
    from torchsnapshot_tpu import CheckpointManager

    state = {"model": StateDict(w=np.arange(1000, dtype=np.float32))}
    from torchsnapshot_tpu import telemetry

    telemetry.set_enabled(True)
    try:
        mgr = CheckpointManager(str(tmp_path), save_interval_steps=1)
        mgr.save(0, state)
        mgr.save(1, state)
    finally:
        telemetry.set_enabled(False)
        telemetry.reset()
    records = history.load_history(str(tmp_path))
    assert [r.get("step") for r in records] == [0, 1]
    # With the bus on, counters ride along.
    assert records[-1].get("bytes_written", 0) > 0


# ---------------------------------------------------------- regression


def test_detect_regression_flags_slowdown():
    records = [{"wall_s": 1.0 + 0.01 * i} for i in range(10)]
    records += [{"wall_s": 1.6} for _ in range(5)]
    v = history.detect_regression(records, threshold=0.25)
    assert v["regressed"] is True
    assert v["recent_p50"] == 1.6
    assert v["ratio"] > 1.5


def test_detect_regression_ok_within_threshold():
    records = [{"wall_s": 1.0} for _ in range(10)] + [{"wall_s": 1.1}] * 5
    v = history.detect_regression(records, threshold=0.25)
    assert v["regressed"] is False


def test_detect_regression_throughput_metric_lower_is_worse():
    records = [{"write_gbps": 2.3} for _ in range(8)] + [
        {"write_gbps": 1.0} for _ in range(4)
    ]
    v = history.detect_regression(records, metric="write_gbps", threshold=0.25)
    assert v["regressed"] is True


def test_detect_regression_insufficient_history_never_fails_ci():
    v = history.detect_regression([{"wall_s": 1.0}, {"wall_s": 9.0}])
    assert v["regressed"] is False
    assert v["reason"] == "insufficient history"


def test_threshold_env(monkeypatch):
    monkeypatch.setenv(history.TREND_THRESHOLD_ENV_VAR, "0.5")
    assert history.trend_threshold() == 0.5
    monkeypatch.setenv(history.TREND_THRESHOLD_ENV_VAR, "junk")
    assert history.trend_threshold() == 0.25


# ------------------------------------------------------- stats --trend


def test_stats_trend_detects_injected_regression_and_exits_1(tmp_path, capsys):
    _seed(tmp_path, [1.0] * 8 + [1.8] * 5, gbps=[2.3] * 8 + [1.2] * 5)
    rc = main(["stats", str(tmp_path), "--trend"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "REGRESSED" in out
    assert "history: 13 committed take(s)" in out


def test_stats_trend_ok_exits_0(tmp_path, capsys):
    _seed(tmp_path, [1.0] * 10)
    assert main(["stats", str(tmp_path), "--trend"]) == 0
    assert "trend[wall_s]: ok" in capsys.readouterr().out


def test_stats_trend_threshold_flag(tmp_path):
    _seed(tmp_path, [1.0] * 8 + [1.2] * 4)  # +20%
    assert main(["stats", str(tmp_path), "--trend"]) == 0  # default 25%
    assert main(
        ["stats", str(tmp_path), "--trend", "--trend-threshold", "0.1"]
    ) == 1


def test_stats_trend_no_history_exits_2(tmp_path, capsys):
    assert main(["stats", str(tmp_path), "--trend"]) == 2
    assert "no usable checkpoint history" in capsys.readouterr().err


# -------------------------------------------------------- openmetrics


_METRIC_LINE = re.compile(
    r"^[a-zA-Z_:][a-zA-Z0-9_:]*(\{[^}]*\})? -?[0-9.eE+-]+$"
)
_META_LINE = re.compile(r"^# (TYPE|HELP|EOF)")


def test_openmetrics_format_sanity(tmp_path, capsys):
    from torchsnapshot_tpu import telemetry

    telemetry.set_enabled(True)
    try:
        state = {"model": StateDict(w=np.arange(10_000, dtype=np.float32))}
        cur = str(tmp_path / "cur")
        Snapshot.take(cur, state)
    finally:
        telemetry.set_enabled(False)
        telemetry.reset()
    assert main(["stats", cur, "--openmetrics"]) == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    assert lines[-1] == "# EOF"
    for line in lines:
        if line.startswith("#"):
            assert _META_LINE.match(line) or line.startswith("# HELP"), line
        else:
            assert _METRIC_LINE.match(line), line
    # Counter SAMPLES end in _total while the TYPE line names the bare
    # family, per the OpenMetrics spec; samples are labeled with the op.
    assert "# TYPE torchsnapshot_tpu_bytes_written counter" in out
    assert "torchsnapshot_tpu_bytes_written_total{" in out
    assert 'op="take"' in out
    assert 'rank="0"' in out
    # The authoritative check, when the reference parser is available:
    # a strict OpenMetrics parser must accept the exposition whole.
    try:
        from prometheus_client.openmetrics import parser
    except ImportError:
        return
    families = list(parser.text_string_to_metric_families(out))
    names = {f.name for f in families}
    assert "torchsnapshot_tpu_bytes_written" in names


def test_openmetrics_escapes_label_values():
    doc = {
        "op": 'ta"ke\n',
        "world_size": 1,
        "ranks": [{"op": "take", "rank": 0, "wall_s": 1.0,
                   "counters": {"bytes_written": 10}}],
    }
    from torchsnapshot_tpu.telemetry.aggregate import merge_summaries

    doc["fleet"] = merge_summaries(doc["ranks"])
    out = render_openmetrics(doc)
    assert '\\"' in out
    assert "\\n" in out
    assert out.endswith("# EOF\n")


def test_openmetrics_json_roundtrip_document(tmp_path):
    """render_openmetrics works from a re-loaded persisted document (the
    exact bytes `stats` reads), not just in-memory dicts."""
    from torchsnapshot_tpu import telemetry

    telemetry.set_enabled(True)
    try:
        state = {"model": StateDict(w=np.arange(1000, dtype=np.float32))}
        cur = str(tmp_path / "cur")
        Snapshot.take(cur, state)
    finally:
        telemetry.set_enabled(False)
        telemetry.reset()
    doc = json.loads(open(os.path.join(cur, ".snapshot_telemetry")).read())
    out = render_openmetrics(doc)
    assert out.splitlines()[-1] == "# EOF"
