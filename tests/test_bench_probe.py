"""bench.py backend-probe retry logic (driver contract robustness).

The probe must retry clean failures within its time budget, respect
cool-downs after killed (timed-out) probes, honor the DtoH floor, and
always fall back to cpu so the driver records a number. Round 6
hardening (VERDICT r5 item 1): subprocesses lead their own process
GROUP and a timeout kills the whole group (the r05 artifact regression
came from orphaned relay children surviving a probe kill and stealing
the core during the timed saves), and the host self-calibrates before
the timing window opens.
"""

from __future__ import annotations

import os
import subprocess
import sys

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import bench  # noqa: E402


class FakeResult:
    """Shape of bench._run_in_own_group's result."""

    def __init__(self, returncode=0, stdout="", stderr="", killed=False):
        self.returncode = returncode
        self.stdout = stdout
        self.stderr = stderr
        self.killed = killed


@pytest.fixture(autouse=True)
def _fast(monkeypatch):
    monkeypatch.setenv("BENCH_PROBE_TIMEOUT_S", "60")
    monkeypatch.setenv("BENCH_PROBE_TOTAL_S", "300")
    monkeypatch.delenv("BENCH_FORCE_CPU", raising=False)
    sleeps = []
    monkeypatch.setattr(bench.time, "sleep", lambda s: sleeps.append(s))
    yield sleeps


def test_probe_success_first_try(monkeypatch):
    monkeypatch.setattr(
        bench,
        "_run_in_own_group",
        lambda cmd, timeout: FakeResult(0, "banner\ntpu 1 2.5000\n"),
    )
    assert bench._probe_backend() == ("tpu", True)


def test_probe_retries_clean_failure_then_succeeds(monkeypatch, _fast):
    calls = []

    def run(cmd, timeout):
        calls.append(1)
        if len(calls) < 3:
            return FakeResult(1, "", "UNAVAILABLE")
        return FakeResult(0, "tpu 1 1.0000\n")

    monkeypatch.setattr(bench, "_run_in_own_group", run)
    assert bench._probe_backend() == ("tpu", True)
    assert len(calls) == 3
    assert _fast == [30, 30]  # one clean-failure pause per failed attempt


def test_probe_killed_gets_longer_cooldown(monkeypatch, _fast):
    calls = []

    def run(cmd, timeout):
        calls.append(1)
        if len(calls) == 1:
            return FakeResult(-9, "", "", killed=True)
        return FakeResult(0, "tpu 1 1.0000\n")

    monkeypatch.setattr(bench, "_run_in_own_group", run)
    assert bench._probe_backend() == ("tpu", True)
    assert _fast == [120]  # killed probes cool down longer


def test_probe_slow_dtoh_falls_back_to_cpu(monkeypatch):
    monkeypatch.setattr(
        bench,
        "_run_in_own_group",
        lambda cmd, timeout: FakeResult(0, "tpu 1 0.0100\n"),  # tunnel DtoH
    )
    # A reachable-but-tunnel-bound chip still reports tpu_reachable=True
    # so the hardware side-leg runs even though the main leg is on cpu.
    assert bench._probe_backend() == ("cpu", True)


def test_probe_exhausts_budget_and_falls_back(monkeypatch, _fast):
    # Fake clock: each sleep advances it, so the budget drains without
    # real waiting.
    clock = [0.0]
    monkeypatch.setattr(bench.time, "monotonic", lambda: clock[0])

    def sleep(s):
        clock[0] += s

    monkeypatch.setattr(bench.time, "sleep", sleep)

    calls = []

    def run(cmd, timeout):
        calls.append(1)
        clock[0] += 50  # each probe consumes wall time
        return FakeResult(1, "", "UNAVAILABLE")

    monkeypatch.setattr(bench, "_run_in_own_group", run)
    assert bench._probe_backend() == ("cpu", False)
    assert 2 <= len(calls) <= 6  # bounded by the 300 s budget


def test_force_cpu_env(monkeypatch):
    monkeypatch.setenv("BENCH_FORCE_CPU", "1")
    assert bench._probe_backend() == ("cpu", False)

def test_tpu_hw_leg_parses_output(monkeypatch):
    out = (
        '{"benchmark": "dma_overlap/ceiling", "dtoh_ceiling_mbps": 15.0, '
        '"host_memcpy_gbps": 1.8}\n'
        '{"benchmark": "dma_overlap/stage", "overlap_ratio": 1.8, '
        '"async_pct_of_ceiling": 160.0}\n'
        '{"benchmark": "dma_overlap/async_take", "step_inflation": 1.02}\n'
        '{"benchmark": "dma_overlap/sync_take", "take_mbps": 12.4, '
        '"state_mb": 600.0, "take_pct_of_ceiling": 82.7, '
        '"bit_exact": true}\n'
    )
    monkeypatch.setattr(
        bench, "_run_in_own_group", lambda cmd, timeout: FakeResult(0, out)
    )
    summary, killed = bench._tpu_hw_leg()
    assert not killed
    assert summary == {
        "dma_overlap_ratio": 1.8,
        "async_step_inflation": 1.02,
        "sync_take_mbps": 12.4,
        "sync_take_state_mb": 600.0,
        "sync_take_bit_exact": True,
        "ceiling_gbps": 0.015,
        "host_memcpy_gbps": 1.8,
        "achieved_pct": 82.7,
        "async_stage_pct_of_ceiling": 160.0,
    }


def test_tpu_hw_leg_without_ceiling_leg(monkeypatch):
    """Older side-leg output (no ceiling record) still summarizes."""
    out = (
        '{"benchmark": "dma_overlap/stage", "overlap_ratio": 1.8}\n'
        '{"benchmark": "dma_overlap/async_take", "step_inflation": 1.02}\n'
        '{"benchmark": "dma_overlap/sync_take", "take_mbps": 12.4, '
        '"bit_exact": true}\n'
    )
    monkeypatch.setattr(
        bench, "_run_in_own_group", lambda cmd, timeout: FakeResult(0, out)
    )
    summary, killed = bench._tpu_hw_leg()
    assert not killed
    assert summary == {
        "dma_overlap_ratio": 1.8,
        "async_step_inflation": 1.02,
        "sync_take_mbps": 12.4,
        "sync_take_state_mb": None,
        "sync_take_bit_exact": True,
    }


def test_tpu_hw_leg_timeout_reports_killed(monkeypatch):
    monkeypatch.setattr(
        bench,
        "_run_in_own_group",
        lambda cmd, timeout: FakeResult(-9, "", "", killed=True),
    )
    assert bench._tpu_hw_leg() == (None, True)


def test_tpu_hw_leg_incomplete_output(monkeypatch):
    out = '{"benchmark": "dma_overlap/stage", "overlap_ratio": 1.8}\n'
    monkeypatch.setattr(
        bench, "_run_in_own_group", lambda cmd, timeout: FakeResult(0, out)
    )
    assert bench._tpu_hw_leg() == (None, False)


def test_run_in_own_group_kills_descendants():
    """A timed-out subprocess's CHILDREN die with it: the r05 failure
    mode was relay children surviving the direct child's kill and
    competing for the core during the timed saves."""
    code = (
        "import subprocess, sys, time\n"
        "subprocess.Popen([sys.executable, '-c', 'import time; time.sleep(60)'])\n"
        "print('spawned', flush=True)\n"
        "time.sleep(60)\n"
    )
    r = bench._run_in_own_group([sys.executable, "-c", code], timeout=3)
    assert r.killed
    # The whole group (leader + grandchild) must be gone.
    with pytest.raises(ProcessLookupError):
        os.killpg(r.pgid, 0)


def test_run_in_own_group_plain_success():
    r = bench._run_in_own_group(
        [sys.executable, "-c", "print('ok')"], timeout=30
    )
    assert not r.killed
    assert r.returncode == 0
    assert "ok" in r.stdout


def test_host_calibration_reports_shape():
    cal = bench._host_calibration()
    assert set(cal) >= {"load1", "cpu_count", "memcpy_gbps", "contaminated"}
    assert isinstance(cal["contaminated"], bool)
    assert cal["memcpy_gbps"] > 0
