"""Pipeline parallelism correctness on the virtual 8-device CPU mesh.

Oracle: sequential application of the same layer stack (the SURVEY §4.1
round-trip-equality pattern applied to pp). Covers forward equality,
gradient equality, dp x pp composition, and snapshot round-trip of
stage-sharded params.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu.parallel import (
    pipeline_param_sharding,
    pipelined_apply,
)

L, B, D = 8, 8, 16


def layer_fn(layer_params, x):
    w, b = layer_params["w"], layer_params["b"]
    return jnp.tanh(x @ w + b)


def make_params(seed: int = 0):
    ks = jax.random.split(jax.random.PRNGKey(seed), 2)
    return {
        "w": jax.random.normal(ks[0], (L, D, D)) * (D**-0.5),
        "b": jax.random.normal(ks[1], (L, D)) * 0.01,
    }


def sequential_apply(params, x):
    def body(h, layer):
        return layer_fn(layer, h), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 4), (4, 8), (8, 8)])
def test_pipeline_matches_sequential(n_stages: int, n_micro: int) -> None:
    mesh = Mesh(np.array(jax.devices()[:n_stages]).reshape(n_stages), ("pipe",))
    params = make_params()
    x = jax.random.normal(jax.random.PRNGKey(1), (B, D))
    ref = sequential_apply(params, x)
    out = jax.jit(
        lambda p, x: pipelined_apply(
            p, x, mesh, layer_fn=layer_fn, n_micro=n_micro
        )
    )(params, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_composes_with_data_parallel() -> None:
    """dp x pp: batch sharded over 'data', layers over 'pipe'."""
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "pipe"))
    params = make_params(seed=2)
    x = jax.random.normal(jax.random.PRNGKey(3), (B, D))
    ref = sequential_apply(params, x)
    xs = jax.device_put(x, NamedSharding(mesh, P("data")))
    ps = jax.device_put(params, pipeline_param_sharding(params, mesh))
    out = jax.jit(
        lambda p, x: pipelined_apply(p, x, mesh, layer_fn=layer_fn, n_micro=4)
    )(ps, xs)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def test_pipeline_gradients_match_sequential() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pipe",))
    params = make_params(seed=4)
    x = jax.random.normal(jax.random.PRNGKey(5), (B, D))

    def loss_p(params):
        return jnp.sum(
            pipelined_apply(params, x, mesh, layer_fn=layer_fn, n_micro=4) ** 2
        )

    def loss_s(params):
        return jnp.sum(sequential_apply(params, x) ** 2)

    g_p = jax.jit(jax.grad(loss_p))(params)
    g_s = jax.grad(loss_s)(params)
    for a, b in zip(jax.tree_util.tree_leaves(g_p), jax.tree_util.tree_leaves(g_s)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b), atol=1e-4)


def test_pipeline_validation_errors() -> None:
    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pipe",))
    params = make_params()
    x = jnp.zeros((B, D))
    with pytest.raises(ValueError, match="not divisible"):
        pipelined_apply(params, x, mesh, layer_fn=layer_fn, n_micro=3)
    mesh3 = Mesh(np.array(jax.devices()[:3]).reshape(3), ("pipe",))
    with pytest.raises(ValueError, match="layers not divisible"):
        pipelined_apply(params, x, mesh3, layer_fn=layer_fn, n_micro=4)
    mesh_nopipe = Mesh(np.array(jax.devices()[:2]).reshape(2), ("data",))
    with pytest.raises(ValueError, match="lacks pipe axis"):
        pipelined_apply(params, x, mesh_nopipe, layer_fn=layer_fn, n_micro=4)


def test_pipeline_params_snapshot_roundtrip(tmp_path) -> None:
    """Stage-sharded (pp) params are just sharded entries to the snapshot
    layer: save on a 4-stage pipe, restore onto a 2-stage pipe."""
    from torchsnapshot_tpu import Snapshot, StateDict

    mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pipe",))
    params = jax.device_put(
        make_params(seed=6), pipeline_param_sharding(make_params(seed=6), mesh4)
    )
    Snapshot.take(str(tmp_path / "s"), {"m": StateDict(params=params)})

    mesh2 = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pipe",))
    dst_params = jax.device_put(
        jax.tree_util.tree_map(jnp.zeros_like, params),
        pipeline_param_sharding(params, mesh2),
    )
    dst = {"m": StateDict(params=dst_params)}
    Snapshot(str(tmp_path / "s")).restore(dst)
    for a, b in zip(
        jax.tree_util.tree_leaves(params),
        jax.tree_util.tree_leaves(dst["m"]["params"]),
    ):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # and the restored 2-stage params still run the pipeline correctly
    x = jax.random.normal(jax.random.PRNGKey(7), (B, D))
    out = jax.jit(
        lambda p, x: pipelined_apply(
            p, x, mesh2, layer_fn=layer_fn, n_micro=4
        )
    )(dst["m"]["params"], x)
    ref = sequential_apply(make_params(seed=6), x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref), atol=1e-5)


def loss_fn(y, t):
    return jnp.mean((y - t) ** 2)


def oracle_value_and_grad(params, x, targets, n_micro):
    """Dense oracle: mean over microbatches of per-microbatch MSE."""

    def total(params):
        xs = x.reshape(n_micro, -1, D)
        ts = targets.reshape(n_micro, -1, D)
        losses = jax.vmap(lambda xm, tm: loss_fn(sequential_apply(params, xm), tm))(xs, ts)
        return jnp.mean(losses)

    return jax.value_and_grad(total)(params)


@pytest.mark.parametrize("n_stages,n_micro", [(2, 4), (4, 8), (8, 8)])
def test_1f1b_matches_dense_oracle(n_stages: int, n_micro: int) -> None:
    from torchsnapshot_tpu.parallel import pipelined_value_and_grad

    mesh = Mesh(np.array(jax.devices()[:n_stages]).reshape(n_stages), ("pipe",))
    params = make_params()
    x = jax.random.normal(jax.random.PRNGKey(2), (B, D))
    targets = jax.random.normal(jax.random.PRNGKey(3), (B, D))

    ref_loss, ref_grads = oracle_value_and_grad(params, x, targets, n_micro)
    loss, grads = jax.jit(
        lambda p, x, t: pipelined_value_and_grad(
            p, x, t, mesh, layer_fn=layer_fn, loss_fn=loss_fn, n_micro=n_micro
        )
    )(params, x, targets)

    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), atol=1e-4
        )


def test_1f1b_composes_with_data_parallel() -> None:
    from torchsnapshot_tpu.parallel import pipelined_value_and_grad

    mesh = Mesh(
        np.array(jax.devices()[:8]).reshape(2, 4), ("data", "pipe")
    )
    params = make_params()
    x = jax.random.normal(jax.random.PRNGKey(4), (B, D))
    targets = jax.random.normal(jax.random.PRNGKey(5), (B, D))
    n_micro = 4

    ref_loss, ref_grads = oracle_value_and_grad(params, x, targets, n_micro)
    loss, grads = jax.jit(
        lambda p, x, t: pipelined_value_and_grad(
            p, x, t, mesh, layer_fn=layer_fn, loss_fn=loss_fn, n_micro=n_micro
        )
    )(params, x, targets)
    np.testing.assert_allclose(float(loss), float(ref_loss), atol=1e-5)
    for k in ("w", "b"):
        np.testing.assert_allclose(
            np.asarray(grads[k]), np.asarray(ref_grads[k]), atol=1e-4
        )


def test_1f1b_training_snapshot_reshard_4_to_2_stages(tmp_path) -> None:
    """Train with 1F1B on 4 stages, snapshot, restore onto 2 stages, keep
    training — losses must continue the same trajectory as an unsharded
    oracle doing the identical SGD steps."""
    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.parallel import pipelined_value_and_grad

    n_micro, lr = 4, 0.05
    x = jax.random.normal(jax.random.PRNGKey(6), (B, D))
    targets = jax.random.normal(jax.random.PRNGKey(7), (B, D))

    def sgd_steps(value_and_grad, params, n):
        losses = []
        for _ in range(n):
            loss, grads = value_and_grad(params)
            params = jax.tree_util.tree_map(
                lambda p, g: p - lr * g, params, grads
            )
            losses.append(float(loss))
        return params, losses

    # oracle trajectory: 4 steps dense
    o_params, o_losses = sgd_steps(
        lambda p: oracle_value_and_grad(p, x, targets, n_micro),
        make_params(seed=9),
        4,
    )

    # pipelined: 2 steps on 4 stages
    mesh4 = Mesh(np.array(jax.devices()[:4]).reshape(4), ("pipe",))
    params = jax.device_put(
        make_params(seed=9), pipeline_param_sharding(make_params(seed=9), mesh4)
    )
    vg4 = jax.jit(
        lambda p: pipelined_value_and_grad(
            p, x, targets, mesh4, layer_fn=layer_fn, loss_fn=loss_fn,
            n_micro=n_micro,
        )
    )
    params, losses_a = sgd_steps(vg4, params, 2)

    # snapshot the pipe-sharded training state
    Snapshot.take(str(tmp_path / "ckpt"), {"m": StateDict(params=params)})

    # restore onto a DIFFERENT stage count and finish training
    mesh2 = Mesh(np.array(jax.devices()[:2]).reshape(2), ("pipe",))
    dst = jax.device_put(
        make_params(seed=0), pipeline_param_sharding(make_params(seed=0), mesh2)
    )
    out = {"m": StateDict(params=dst)}
    Snapshot(str(tmp_path / "ckpt")).restore(out)
    vg2 = jax.jit(
        lambda p: pipelined_value_and_grad(
            p, x, targets, mesh2, layer_fn=layer_fn, loss_fn=loss_fn,
            n_micro=n_micro,
        )
    )
    _, losses_b = sgd_steps(vg2, out["m"]["params"], 2)

    np.testing.assert_allclose(losses_a + losses_b, o_losses, atol=1e-4)
