"""Real-cloud storage tests, env-gated like the reference's
(tests/test_s3_storage_plugin.py:29-86, test_gcs_storage_plugin.py:30-87).

Skipped unless credentials + opt-in env vars are present:

  TORCHSNAPSHOT_TPU_ENABLE_AWS_TEST=1 TORCHSNAPSHOT_TPU_AWS_TEST_BUCKET=...
  TORCHSNAPSHOT_TPU_ENABLE_GCP_TEST=1 TORCHSNAPSHOT_TPU_GCP_TEST_BUCKET=...

The fake-backed suites (test_s3_storage_plugin.py /
test_gcs_storage_plugin.py) cover the plugin LOGIC unconditionally;
these validate the real SDK/auth/network path where a bucket exists.
"""

from __future__ import annotations

import os
import uuid

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot, StateDict

AWS_GATE = "TORCHSNAPSHOT_TPU_ENABLE_AWS_TEST"
GCP_GATE = "TORCHSNAPSHOT_TPU_ENABLE_GCP_TEST"


def _gate_on(name: str) -> bool:
    # Same off-convention as the library's env flags: unset/0/empty/false
    # all mean off (batcher.batching_enabled).
    return os.environ.get(name, "0") not in ("0", "", "false")


aws_gated = pytest.mark.skipif(
    not _gate_on(AWS_GATE),
    reason=f"set {AWS_GATE}=1 and TORCHSNAPSHOT_TPU_AWS_TEST_BUCKET to run "
    "against real S3",
)
gcp_gated = pytest.mark.skipif(
    not _gate_on(GCP_GATE),
    reason=f"set {GCP_GATE}=1 and TORCHSNAPSHOT_TPU_GCP_TEST_BUCKET to run "
    "against real GCS",
)


def _bucket(kind: str) -> str:
    var = f"TORCHSNAPSHOT_TPU_{kind}_TEST_BUCKET"
    bucket = os.environ.get(var)
    if not bucket:
        # Never guess a bucket name: a squattable default could send real
        # snapshot data to a third party's bucket.
        pytest.skip(f"{var} not set; refusing to guess a bucket name")
    return bucket


def _roundtrip(url: str) -> None:
    state = StateDict(
        w=np.random.default_rng(0).standard_normal(250_000).astype(np.float32),
        step=7,
    )
    try:
        Snapshot.take(url, {"app": state})
        dst = StateDict(w=np.zeros(250_000, np.float32), step=0)
        Snapshot(url).restore({"app": dst})
        np.testing.assert_array_equal(dst["w"], state["w"])
        assert dst["step"] == 7
    finally:
        _cleanup_snapshot(url)


def _cleanup_snapshot(url: str) -> None:
    """Best-effort: delete every payload the manifest names, then the
    metadata — gated runs must not accrue orphaned objects in the test
    bucket."""
    import asyncio

    from torchsnapshot_tpu.cli import _entry_payloads
    from torchsnapshot_tpu.storage_plugin import url_to_storage_plugin

    try:
        meta = Snapshot(url).metadata
    except Exception:
        return  # take never committed; nothing durable to clean
    locations = {
        location
        for e in meta.manifest.values()
        for location, _, _, _, _ in _entry_payloads(e)
    }
    plugin = url_to_storage_plugin(url)

    async def run() -> None:
        for location in locations:
            try:
                await plugin.delete(location)
            except Exception:
                pass
        try:
            await plugin.delete(".snapshot_metadata")
        finally:
            await plugin.close()

    asyncio.new_event_loop().run_until_complete(run())


def _plugin_ops(plugin) -> None:
    import asyncio

    from torchsnapshot_tpu.io_types import ReadIO, WriteIO

    async def run() -> None:
        payload = os.urandom(100_000)
        await plugin.write(WriteIO(path="blob", buf=payload))
        read_io = ReadIO(path="blob")
        await plugin.read(read_io)
        assert bytes(read_io.buf) == payload
        ranged = ReadIO(path="blob", byte_range=(100, 200))
        await plugin.read(ranged)
        assert bytes(ranged.buf) == payload[100:200]
        await plugin.delete("blob")
        await plugin.close()

    asyncio.new_event_loop().run_until_complete(run())


@aws_gated
def test_s3_snapshot_roundtrip_real_bucket() -> None:
    _roundtrip(f"s3://{_bucket('AWS')}/{uuid.uuid4()}")


@aws_gated
def test_s3_write_read_delete_real_bucket() -> None:
    from torchsnapshot_tpu.storage_plugins.s3 import S3StoragePlugin

    _plugin_ops(S3StoragePlugin(f"{_bucket('AWS')}/{uuid.uuid4()}"))


@gcp_gated
def test_gcs_snapshot_roundtrip_real_bucket() -> None:
    _roundtrip(f"gs://{_bucket('GCP')}/{uuid.uuid4()}")


@gcp_gated
def test_gcs_write_read_delete_real_bucket() -> None:
    from torchsnapshot_tpu.storage_plugins.gcs import GCSStoragePlugin

    _plugin_ops(GCSStoragePlugin(f"{_bucket('GCP')}/{uuid.uuid4()}"))


def test_gate_off_values_skip(monkeypatch) -> None:
    """Exporting the gate as 0/empty/false must keep the suite OFF —
    matching the library's env-flag convention."""
    for off in ("0", "", "false"):
        monkeypatch.setenv(AWS_GATE, off)
        assert not _gate_on(AWS_GATE)
    monkeypatch.setenv(AWS_GATE, "1")
    assert _gate_on(AWS_GATE)
