"""Sub-chunk streaming write pipeline tests.

Three layers of coverage, mirroring the contract's seams:

- **Storage-plugin contract**: for every plugin (fs real, s3/gcs fakes,
  and the buffered default fallback), a streamed write must produce a
  byte-identical object to a buffered write of the same payload, and a
  mid-stream failure must leave NO partial object at the final path
  (fs: temp-file + os.replace atomicity — no tmp litter either).
- **Scheduler budget accounting**: streamed entries charge the budget
  their in-flight sub-chunk window, never their full size — peak staged
  memory stays under the per-rank budget even when one entry exceeds it.
- **End-to-end**: a streamed ``Snapshot.take`` records the same
  checksums as a buffered one, verifies on restore, and round-trips
  bit-exactly; the I/O governor adapts sub-chunk size within env bounds
  and resolves the preverify gate from measured rates.
"""

import asyncio
import os

import numpy as np
import pytest

from torchsnapshot_tpu.io_types import (
    BufferStager,
    StoragePlugin,
    WriteIO,
    WriteReq,
    WriteStream,
)
from torchsnapshot_tpu.scheduler import (
    IOGovernor,
    execute_write_reqs,
    io_governor,
)
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


async def _chunks_of(payload: bytes, n: int):
    for lo in range(0, len(payload), n):
        yield payload[lo : lo + n]


async def _failing_chunks(payload: bytes, n: int, fail_after: int):
    sent = 0
    for lo in range(0, len(payload), n):
        if sent == fail_after:
            raise RuntimeError("injected mid-stream staging failure")
        yield payload[lo : lo + n]
        sent += 1


# --------------------------------------------------------------- contract


def test_fs_streamed_equals_buffered(tmp_path, loop) -> None:
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1 << 20)
    loop.run_until_complete(plugin.write(WriteIO(path="buffered", buf=payload)))
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(
                path="a/streamed",
                nbytes=len(payload),
                chunks=_chunks_of(payload, 100_000),
            )
        )
    )
    assert (tmp_path / "a" / "streamed").read_bytes() == (
        tmp_path / "buffered"
    ).read_bytes()


def test_fs_streamed_atomic_on_midstream_failure(tmp_path, loop) -> None:
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1 << 20)
    with pytest.raises(RuntimeError, match="injected"):
        loop.run_until_complete(
            plugin.write_stream(
                WriteStream(
                    path="dst",
                    nbytes=len(payload),
                    chunks=_failing_chunks(payload, 100_000, fail_after=3),
                )
            )
        )
    # No partial object at the final path, no temp litter.
    assert not (tmp_path / "dst").exists()
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []


def test_fs_streamed_short_stream_rejected(tmp_path, loop) -> None:
    """A stream that under-produces must fail loudly, not commit a
    truncated object."""
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(100_000)
    with pytest.raises(IOError, match="short write stream"):
        loop.run_until_complete(
            plugin.write_stream(
                WriteStream(
                    path="dst",
                    nbytes=len(payload) + 1,
                    chunks=_chunks_of(payload, 30_000),
                )
            )
        )
    assert not (tmp_path / "dst").exists()


def test_buffered_fallback_plugin(tmp_path, loop) -> None:
    """A plugin that doesn't override write_stream gets the buffered
    default: same bytes, via its plain write()."""

    class Plain(StoragePlugin):
        def __init__(self):
            self.writes = {}

        async def write(self, write_io):
            self.writes[write_io.path] = bytes(write_io.buf)

        async def read(self, read_io):
            raise NotImplementedError

        async def delete(self, path):
            raise NotImplementedError

        async def close(self):
            pass

    plugin = Plain()
    assert not getattr(plugin, "supports_streaming")
    payload = os.urandom(300_000)
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(path="p", nbytes=len(payload), chunks=_chunks_of(payload, 77_000))
        )
    )
    assert plugin.writes["p"] == payload


def test_s3_streamed_multipart_equals_buffered(loop) -> None:
    from test_s3_storage_plugin import FakeMultipartS3Client, make_plugin

    payload = os.urandom(1 << 20)
    client = FakeMultipartS3Client()
    plugin = make_plugin(client, multipart_threshold=256 << 10)
    # Force small parts so the stream spans several.
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    orig = s3mod.MULTIPART_PART_BYTES
    s3mod.MULTIPART_PART_BYTES = 256 << 10
    try:
        loop.run_until_complete(
            plugin.write_stream(
                WriteStream(
                    path="obj", nbytes=len(payload), chunks=_chunks_of(payload, 100_000)
                )
            )
        )
    finally:
        s3mod.MULTIPART_PART_BYTES = orig
    assert client.store[("fake-bucket", "prefix/obj")] == payload


def test_s3_streamed_small_payload_single_put(loop) -> None:
    from test_s3_storage_plugin import FakeS3Client, make_plugin

    payload = os.urandom(200_000)
    client = FakeS3Client()
    plugin = make_plugin(client)  # default threshold far above payload
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(path="obj", nbytes=len(payload), chunks=_chunks_of(payload, 64_000))
        )
    )
    assert client.store[("fake-bucket", "prefix/obj")] == payload


def test_s3_streamed_midstream_failure_aborts_upload(loop) -> None:
    from test_s3_storage_plugin import FakeMultipartS3Client, make_plugin

    payload = os.urandom(1 << 20)
    client = FakeMultipartS3Client()
    plugin = make_plugin(client, multipart_threshold=256 << 10)
    import torchsnapshot_tpu.storage_plugins.s3 as s3mod

    orig = s3mod.MULTIPART_PART_BYTES
    s3mod.MULTIPART_PART_BYTES = 256 << 10
    try:
        with pytest.raises(RuntimeError, match="injected"):
            loop.run_until_complete(
                plugin.write_stream(
                    WriteStream(
                        path="obj",
                        nbytes=len(payload),
                        chunks=_failing_chunks(payload, 100_000, fail_after=4),
                    )
                )
            )
    finally:
        s3mod.MULTIPART_PART_BYTES = orig
    assert ("fake-bucket", "prefix/obj") not in client.store
    assert client.aborted  # upload aborted server-side, no orphaned parts


def test_gcs_streamed_equals_buffered(loop) -> None:
    from test_gcs_storage_plugin import FakeBucket, make_plugin

    payload = os.urandom(1 << 20)
    bucket = FakeBucket()
    plugin = make_plugin(bucket, chunk_size_bytes=256 << 10)
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(
                path="obj", nbytes=len(payload), chunks=_chunks_of(payload, 100_000)
            )
        )
    )
    assert bucket.store["prefix/obj"] == payload


def test_gcs_streamed_retry_replays_stream(loop) -> None:
    """A transient upload failure mid-stream: the retained-chunk stream
    rewinds to zero and the retry uploads the COMPLETE object."""
    from test_gcs_storage_plugin import FakeBucket, make_plugin

    payload = os.urandom(1 << 20)
    bucket = FakeBucket(fail_times=1)
    plugin = make_plugin(bucket, chunk_size_bytes=256 << 10)
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(
                path="obj", nbytes=len(payload), chunks=_chunks_of(payload, 100_000)
            )
        )
    )
    assert bucket.store["prefix/obj"] == payload
    assert bucket.blobs["prefix/obj"].upload_attempts == 2


def test_gcs_streamed_midstream_failure_propagates(loop) -> None:
    from test_gcs_storage_plugin import FakeBucket, make_plugin

    payload = os.urandom(1 << 20)
    bucket = FakeBucket()
    plugin = make_plugin(bucket, chunk_size_bytes=256 << 10)
    with pytest.raises(RuntimeError, match="injected"):
        loop.run_until_complete(
            plugin.write_stream(
                WriteStream(
                    path="obj",
                    nbytes=len(payload),
                    chunks=_failing_chunks(payload, 100_000, fail_after=2),
                )
            )
        )
    assert "prefix/obj" not in bucket.store


# -------------------------------------------------- scheduler accounting


class StreamingStager(BufferStager):
    """Streams a synthetic payload while tracking LIVE staged bytes so
    the test can assert the budget actually bounds sub-chunk memory."""

    live_bytes = 0
    peak_bytes = 0

    def __init__(self, total: int, fill: int) -> None:
        self.total = total
        self.fill = fill

    async def stage_buffer(self, executor=None):
        return bytes([self.fill]) * self.total

    def get_staging_cost_bytes(self) -> int:
        return self.total

    def can_stream(self, sub_chunk_bytes: int) -> bool:
        return self.total >= 2 * sub_chunk_bytes

    async def stage_stream(self, executor, sub_chunk_bytes: int):
        cls = StreamingStager
        for lo in range(0, self.total, sub_chunk_bytes):
            n = min(sub_chunk_bytes, self.total - lo)
            cls.live_bytes += n
            cls.peak_bytes = max(cls.peak_bytes, cls.live_bytes)
            await asyncio.sleep(0.001)  # let writes interleave
            yield bytes([self.fill]) * n
            cls.live_bytes -= n


class CountingStreamFS(FSStoragePlugin):
    stream_calls = 0
    buffered_calls = 0

    async def write_stream(self, stream):
        CountingStreamFS.stream_calls += 1
        await super().write_stream(stream)

    async def write(self, write_io):
        CountingStreamFS.buffered_calls += 1
        await super().write(write_io)


def _reset_counters():
    StreamingStager.live_bytes = 0
    StreamingStager.peak_bytes = 0
    CountingStreamFS.stream_calls = 0
    CountingStreamFS.buffered_calls = 0


def test_streamed_budget_charges_sub_chunks(tmp_path, loop, monkeypatch) -> None:
    """Entries far larger than the budget stream under it: the budget
    charges the in-flight sub-chunk window (2 sub-chunks/entry), so peak
    live staged bytes stays bounded while the data still lands whole."""
    _reset_counters()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(64 << 10))
    storage = CountingStreamFS(str(tmp_path))
    total = 1 << 20  # 16x the sub-chunk, far over the budget below
    reqs = [
        WriteReq(path=f"obj_{i}", buffer_stager=StreamingStager(total, i))
        for i in range(3)
    ]
    budget = 300 << 10  # < one entry; >= one entry's 2-sub-chunk window
    pending = loop.run_until_complete(
        execute_write_reqs(reqs, storage, budget, rank=0, allow_streaming=True)
    )
    pending.sync_complete(loop)
    assert CountingStreamFS.stream_calls == 3
    assert StreamingStager.peak_bytes <= budget
    for i in range(3):
        assert (tmp_path / f"obj_{i}").read_bytes() == bytes([i]) * total


def test_streaming_respects_plugin_opt_in(tmp_path, loop, monkeypatch) -> None:
    """A plugin without supports_streaming never sees streamed entries
    (the buffered fallback would break sub-chunk budget accounting)."""
    _reset_counters()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(64 << 10))

    class NoStreamFS(CountingStreamFS):
        supports_streaming = False

    storage = NoStreamFS(str(tmp_path))
    reqs = [WriteReq(path="obj", buffer_stager=StreamingStager(1 << 20, 5))]
    pending = loop.run_until_complete(
        execute_write_reqs(reqs, storage, 1 << 30, rank=0, allow_streaming=True)
    )
    pending.sync_complete(loop)
    assert CountingStreamFS.stream_calls == 0
    assert CountingStreamFS.buffered_calls == 1
    assert (tmp_path / "obj").read_bytes() == bytes([5]) * (1 << 20)


def test_streaming_off_for_async_path(tmp_path, loop, monkeypatch) -> None:
    """allow_streaming=False (async_take's mode) stages whole buffers
    even when stager and plugin both support streaming."""
    _reset_counters()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(64 << 10))
    storage = CountingStreamFS(str(tmp_path))
    reqs = [WriteReq(path="obj", buffer_stager=StreamingStager(1 << 20, 9))]
    pending = loop.run_until_complete(
        execute_write_reqs(reqs, storage, 1 << 30, rank=0, allow_streaming=False)
    )
    pending.sync_complete(loop)
    assert CountingStreamFS.stream_calls == 0
    assert (tmp_path / "obj").read_bytes() == bytes([9]) * (1 << 20)


def test_streamed_failure_propagates_and_cancels(tmp_path, loop, monkeypatch) -> None:
    _reset_counters()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(64 << 10))

    class FailingStager(StreamingStager):
        async def stage_stream(self, executor, sub_chunk_bytes):
            yield b"x" * sub_chunk_bytes
            raise RuntimeError("injected staging failure")

    storage = CountingStreamFS(str(tmp_path))
    reqs = [
        WriteReq(path="bad", buffer_stager=FailingStager(1 << 20, 0)),
        WriteReq(path="good", buffer_stager=StreamingStager(1 << 20, 1)),
    ]
    with pytest.raises(RuntimeError, match="injected staging failure"):
        pending = loop.run_until_complete(
            execute_write_reqs(reqs, storage, 1 << 30, rank=0, allow_streaming=True)
        )
        pending.sync_complete(loop)
    assert not (tmp_path / "bad").exists()


# ------------------------------------------------------------ end to end


def test_take_streams_and_roundtrips(tmp_path, monkeypatch) -> None:
    """Sync take streams large plain entries; checksums are recorded,
    verified on restore, and identical to a buffered take's."""
    from torchsnapshot_tpu import Snapshot, StateDict

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(128 << 10))
    state = {
        "app": StateDict(
            w=np.arange(500_000, dtype=np.float32).reshape(500, 1000),
            small=np.ones(16, np.float64),
        )
    }
    Snapshot.take(str(tmp_path / "streamed"), state)
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_WRITES", "0")
    Snapshot.take(str(tmp_path / "buffered"), state)

    import json

    def checksums(p):
        meta = json.loads((tmp_path / p / ".snapshot_metadata").read_text())
        found = {}

        def walk(node):
            if isinstance(node, dict):
                if node.get("checksum") and node.get("location"):
                    # Keyed by RELATIVE payload name: the two snapshots
                    # live under different roots but share the layout.
                    found[node["location"]] = node["checksum"]
                for v in node.values():
                    walk(v)
            elif isinstance(node, list):
                for v in node:
                    walk(v)

        walk(meta["manifest"])
        return found

    streamed, buffered = checksums("streamed"), checksums("buffered")
    assert streamed and streamed == buffered

    monkeypatch.delenv("TORCHSNAPSHOT_TPU_STREAM_WRITES", raising=False)
    dst = {
        "app": StateDict(
            w=np.zeros((500, 1000), np.float32), small=np.zeros(16, np.float64)
        )
    }
    Snapshot(str(tmp_path / "streamed")).restore(dst)  # verifies checksums
    assert np.array_equal(dst["app"]["w"], state["app"]["w"])
    assert np.array_equal(dst["app"]["small"], state["app"]["small"])


def test_stream_kill_switch(tmp_path, monkeypatch) -> None:
    _reset_counters()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_STREAM_WRITES", "0")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(64 << 10))
    from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager

    stager = ArrayBufferStager(np.ones(1 << 20, np.uint8))
    assert not stager.can_stream(64 << 10)


def test_stager_streamed_bytes_match_buffered(loop, monkeypatch) -> None:
    """ArrayBufferStager.stage_stream concatenation == stage_buffer."""
    from concurrent.futures import ThreadPoolExecutor

    from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager
    from torchsnapshot_tpu.manifest import ArrayEntry

    arr = np.arange(200_000, dtype=np.int32).reshape(400, 500)

    async def collect():
        entry = ArrayEntry(
            location="x",
            serializer="buffer_protocol",
            dtype="int32",
            shape=list(arr.shape),
            replicated=False,
        )
        stager = ArrayBufferStager(arr, entry)
        assert stager.can_stream(100_000)
        with ThreadPoolExecutor(2) as pool:
            parts = []
            async for chunk in stager.stage_stream(pool, 100_000):
                parts.append(bytes(memoryview(chunk)))
        return b"".join(parts), entry.checksum

    streamed, checksum = loop.run_until_complete(collect())
    assert streamed == arr.tobytes()
    if checksum is not None:
        from torchsnapshot_tpu.integrity import verify_checksum

        verify_checksum(streamed, checksum, "x")  # must not raise


def test_stager_consistency_copy_stream(loop) -> None:
    """Outside zero-copy staging (copy_for_consistency=True) the stream
    bounces through pooled slabs: mutating the source AFTER a chunk is
    yielded must not corrupt already-yielded bytes."""
    from concurrent.futures import ThreadPoolExecutor

    from torchsnapshot_tpu.io_preparers.array import ArrayBufferStager

    arr = np.zeros(500_000, np.uint8)
    expect = arr.tobytes()

    async def collect():
        stager = ArrayBufferStager(arr)
        assert stager.copy_for_consistency
        with ThreadPoolExecutor(2) as pool:
            parts = []
            async for chunk in stager.stage_stream(pool, 100_000):
                parts.append(chunk)  # keep the buffer, not a copy
                arr[:] = 255  # mutate source mid-stream
        return parts

    parts = loop.run_until_complete(collect())
    first = bytes(memoryview(parts[0]))
    assert first == expect[: len(first)]  # yielded bytes are snapshots


# -------------------------------------------------------------- governor


def test_governor_sub_chunk_adapts_within_bounds(monkeypatch) -> None:
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", raising=False)
    gov = IOGovernor()
    assert gov.sub_chunk_bytes() == 64 << 20  # default, no measurements
    gov.record_write("FSStoragePlugin", 10 << 30, 1.0)  # 10 GB/s
    assert gov.sub_chunk_bytes("FSStoragePlugin") == 256 << 20  # clamped max
    gov2 = IOGovernor()
    gov2.record_write("S3StoragePlugin", 50 << 20, 1.0)  # 50 MB/s
    assert gov2.sub_chunk_bytes("S3StoragePlugin") == 8 << 20  # clamped min


def test_governor_env_pin_wins(monkeypatch) -> None:
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(32 << 20))
    gov = IOGovernor()
    gov.record_write("FSStoragePlugin", 10 << 30, 1.0)
    assert gov.sub_chunk_bytes("FSStoragePlugin") == 32 << 20
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_IO_CONCURRENCY", "3")
    assert gov.io_concurrency() == 3


def test_governor_preverify_gate(monkeypatch) -> None:
    monkeypatch.delenv("TORCHSNAPSHOT_TPU_PREVERIFY", raising=False)
    gov = IOGovernor()
    # No measurements: status-quo verify.
    assert gov.should_preverify()
    # Hash-bound regime (slow storage): verify.
    gov.record_read("S3StoragePlugin", 50 << 20, 1.0)
    gov.record_hash(2 << 30, 1.0)
    assert gov.should_preverify()
    # Read-bound regime (fast storage, slow hasher): skip.
    gov2 = IOGovernor()
    gov2.record_read("FSStoragePlugin", 6 << 30, 1.0)
    gov2.record_hash(1 << 30, 1.0)
    assert not gov2.should_preverify()
    # Env overrides beat measurements both ways.
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PREVERIFY", "always")
    assert gov2.should_preverify()
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_PREVERIFY", "never")
    assert not gov.should_preverify()


def test_scheduler_records_rates(tmp_path, loop) -> None:
    """Real writes/reads feed the process governor's EWMA tables."""
    from torchsnapshot_tpu import Snapshot, StateDict

    state = {"app": StateDict(w=np.ones(100_000, np.float32))}
    Snapshot.take(str(tmp_path / "s"), state)
    rates = io_governor().measured_rates()
    assert rates["write_bps"].get("FSStoragePlugin", 0) > 0
