"""save_dtype: store checkpoints downcast, restore widens back.

``Snapshot.take(..., save_dtype={"glob": "dtype"})`` downcasts matching
float array leaves before staging — on device for jax arrays (astype
preserves sharding; DtoH then moves half the bytes for fp32 states) — and
the manifest records the stored dtype, so cast-on-restore widens back into
the destination's params transparently. Int and object leaves under a glob
are left alone (same_kind casts only).

No reference analogue (torchsnapshot stores tensors byte-exact only); the
orbax counterpart is SaveArgs dtype casting.
"""

from __future__ import annotations

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from torchsnapshot_tpu import CheckpointManager, Snapshot, StateDict
from torchsnapshot_tpu.manifest import ArrayEntry, ShardedArrayEntry


def _entries(path):
    from torchsnapshot_tpu.manifest import get_manifest_for_rank

    return get_manifest_for_rank(Snapshot(path=path).metadata, 0)


def _payload_bytes(path):
    total = 0
    for dp, _, fs in os.walk(path):
        for f in fs:
            if not f.startswith("."):
                total += os.path.getsize(os.path.join(dp, f))
    return total


def test_downcast_halves_storage_and_restores_back(tmp_path):
    src_w = np.arange(4096, dtype=np.float32)
    state = {"m": StateDict(w=jnp.asarray(src_w), step=np.int64(7))}
    full = str(tmp_path / "full")
    half = str(tmp_path / "half")
    Snapshot.take(full, state)
    Snapshot.take(half, state, save_dtype={"m/**": "bfloat16"})

    # Stored dtype is recorded; the int leaf is untouched.
    ents = _entries(half)
    assert ents["m/w"].dtype == "bfloat16"
    # Payload bytes roughly halve (metadata excluded above).
    assert _payload_bytes(half) < 0.6 * _payload_bytes(full)

    # Restore widens back into fp32 params.
    dst = {"m": StateDict(w=jnp.zeros(4096, jnp.float32), step=np.int64(0))}
    Snapshot(path=half).restore(dst)
    assert dst["m"]["w"].dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(dst["m"]["w"]), src_w.astype("bfloat16").astype(np.float32)
    )
    assert int(dst["m"]["step"]) == 7


def test_int_array_leaves_under_float_glob_stay_int(tmp_path):
    """The optax trap: ``count`` is an int32 ARRAY (not a scalar). numpy's
    same_kind alone would permit int->float — corrupting counts > 256 and
    making the snapshot unrestorable into the original int destination
    (restore forbids float->int) — so the class rule must keep it int."""
    state = {
        "opt": StateDict(
            mu=jnp.ones(64, jnp.float32),
            count=jnp.asarray(np.full(4, 301, np.int32)),
            flag=np.array([True, False]),
        )
    }
    path = str(tmp_path / "s")
    Snapshot.take(path, state, save_dtype={"opt/**": "bfloat16"})
    ents = _entries(path)
    assert ents["opt/mu"].dtype == "bfloat16"
    assert ents["opt/count"].dtype == "int32"
    assert ents["opt/flag"].dtype == "bool"

    dst = {
        "opt": StateDict(
            mu=jnp.zeros(64, jnp.float32),
            count=jnp.zeros(4, jnp.int32),
            flag=np.array([False, False]),
        )
    }
    Snapshot(path=path).restore(dst)
    np.testing.assert_array_equal(np.asarray(dst["opt"]["count"]), [301] * 4)


def test_int_to_int_narrowing_by_explicit_glob(tmp_path):
    # numpy leaves both ways: jax silently downgrades int64 under the
    # suite's JAX_ENABLE_X64=0, which would mask the cast being tested.
    state = {"m": StateDict(ids=np.arange(128, dtype=np.int64))}
    path = str(tmp_path / "s")
    Snapshot.take(path, state, save_dtype={"m/ids": "int32"})
    assert _entries(path)["m/ids"].dtype == "int32"
    dst = np.zeros(128, np.int64)
    Snapshot(path=path).restore({"m": StateDict(ids=dst)})
    np.testing.assert_array_equal(dst, np.arange(128))


def test_invalid_dtype_name_fails_fast(tmp_path):
    state = {"m": StateDict(w=jnp.ones(4, jnp.float32))}
    with pytest.raises(ValueError, match="save_dtype.*bf16"):
        Snapshot.take(str(tmp_path / "s"), state, save_dtype={"m/**": "bf16"})
    assert not os.path.exists(str(tmp_path / "s"))
    with pytest.raises(ValueError, match="save_dtype"):
        Snapshot.async_take(
            str(tmp_path / "s2"), state, save_dtype={"m/**": "half"}
        )


def test_non_matching_globs_untouched(tmp_path):
    state = {
        "m": StateDict(w=jnp.ones(64, jnp.float32)),
        "opt": StateDict(mu=jnp.ones(64, jnp.float32)),
    }
    path = str(tmp_path / "s")
    Snapshot.take(path, state, save_dtype={"opt/**": "bfloat16"})
    ents = _entries(path)
    assert ents["m/w"].dtype == "float32"
    assert ents["opt/mu"].dtype == "bfloat16"


def test_first_matching_glob_wins(tmp_path):
    state = {"m": StateDict(a=jnp.ones(8, jnp.float32), b=jnp.ones(8, jnp.float32))}
    path = str(tmp_path / "s")
    Snapshot.take(
        path, state, save_dtype={"m/a": "float32", "m/**": "bfloat16"}
    )
    ents = _entries(path)
    assert ents["m/a"].dtype == "float32"  # explicit no-op match shields m/a
    assert ents["m/b"].dtype == "bfloat16"


def test_sharded_downcast_preserves_sharding(tmp_path):
    mesh = Mesh(np.array(jax.devices()[:8]).reshape(4, 2), ("x", "y"))
    data = np.arange(32 * 16, dtype="float32").reshape(32, 16)
    src = jax.device_put(jnp.asarray(data), NamedSharding(mesh, P("x", "y")))
    path = str(tmp_path / "s")
    Snapshot.take(path, {"m": StateDict(w=src)}, save_dtype={"m/**": "bfloat16"})

    ent = _entries(path)["m/w"]
    assert isinstance(ent, ShardedArrayEntry)
    assert ent.dtype == "bfloat16"

    dst = jax.device_put(
        jnp.zeros((32, 16), jnp.float32), NamedSharding(mesh, P("x", "y"))
    )
    out = {"m": StateDict(w=dst)}
    Snapshot(path=path).restore(out)
    restored = out["m"]["w"]
    assert restored.dtype == jnp.float32
    assert restored.sharding == dst.sharding
    np.testing.assert_array_equal(
        np.asarray(restored), data.astype("bfloat16").astype(np.float32)
    )


def test_async_take_save_dtype(tmp_path):
    state = {"m": StateDict(w=jnp.arange(1024, dtype=jnp.float32))}
    path = str(tmp_path / "s")
    pending = Snapshot.async_take(path, state, save_dtype={"m/**": "bfloat16"})
    pending.wait()
    assert _entries(path)["m/w"].dtype == "bfloat16"


def test_manager_save_dtype_end_to_end(tmp_path):
    mgr = CheckpointManager(str(tmp_path), save_dtype={"m/**": "bfloat16"})
    state = {"m": StateDict(w=jnp.arange(256, dtype=jnp.float32))}
    mgr.warmup(state)  # warms at the CONVERTED slab sizes
    assert mgr.save(0, state)
    ents = _entries(mgr.path_for(0))
    assert ents["m/w"].dtype == "bfloat16"
    dst = {"m": StateDict(w=jnp.zeros(256, jnp.float32))}
    Snapshot(path=mgr.path_for(0)).restore(dst)
    assert dst["m"]["w"].dtype == jnp.float32


def test_warmup_sizes_follow_save_dtype():
    """The pool must be warmed at the converted slab size, or the first
    real save misses the exact-size free list entirely."""
    from torchsnapshot_tpu.io_preparers import array as array_mod

    if not array_mod._BUFFER_PROTOCOL_OK or not __import__(
        "torchsnapshot_tpu._native", fromlist=["native_available"]
    ).native_available():
        pytest.skip("staging pool inactive on this host")

    state = {"m": StateDict(w=np.ones(100_000, np.float32))}
    warmed = array_mod.warmup_staging(state, save_dtype={"m/**": "bfloat16"})
    # 100k fp32 elements stored as bf16 = 200 kB slab, not 400 kB.
    # (prewarm returns bytes newly faulted; 0 if this exact size is
    # already pooled from an earlier test — check the pool either way.)
    with array_mod._staging_pool._lock:
        assert 200_000 in array_mod._staging_pool._free
    assert warmed in (0, 200_000)


def test_save_dtype_upcast_also_works(tmp_path):
    """The mapping is a cast, not only a downcast: same_kind either way."""
    state = {"m": StateDict(w=jnp.arange(64, dtype=jnp.bfloat16))}
    path = str(tmp_path / "s")
    Snapshot.take(path, state, save_dtype={"m/**": "float32"})
    assert _entries(path)["m/w"].dtype == "float32"


def test_fp8_quarter_size_storage(tmp_path):
    """fp8 is in the float class: 4x smaller storage for tolerant state
    (e.g. EMA shadows); restore widens back through the same machinery."""
    src = np.linspace(-2, 2, 1024, dtype=np.float32)
    path = str(tmp_path / "s")
    Snapshot.take(
        path,
        {"m": StateDict(w=jnp.asarray(src))},
        save_dtype={"m/**": "float8_e4m3fn"},
    )
    assert _entries(path)["m/w"].dtype == "float8_e4m3fn"
    dst = {"m": StateDict(w=jnp.zeros(1024, jnp.float32))}
    Snapshot(path=path).restore(dst)
    import ml_dtypes

    np.testing.assert_array_equal(
        np.asarray(dst["m"]["w"]),
        src.astype(ml_dtypes.float8_e4m3fn).astype(np.float32),
    )


def test_composes_with_incremental_and_compression(tmp_path):
    """Digests are computed on the CONVERTED bytes, so an unchanged leaf
    dedups across a save_dtype chain, and compression applies on top."""
    mgr = CheckpointManager(
        str(tmp_path),
        incremental=True,
        compression="zstd",
        save_dtype={"m/**": "bfloat16"},
    )
    w = jnp.arange(4096, dtype=jnp.float32)
    frozen = jnp.ones(4096, jnp.float32)
    assert mgr.save(0, {"m": StateDict(w=w, frozen=frozen)})
    assert mgr.save(1, {"m": StateDict(w=w * 2, frozen=frozen)})

    ents = _entries(mgr.path_for(1))
    assert ents["m/w"].dtype == "bfloat16"
    # The unchanged leaf's payload points back at step 0's bytes.
    frozen_ent = ents["m/frozen"]
    inner = (
        frozen_ent.chunks[0].array
        if hasattr(frozen_ent, "chunks")
        else frozen_ent
    )
    assert inner.origin is not None and "step_0000000000" in inner.origin

    dst = {
        "m": StateDict(
            w=jnp.zeros(4096, jnp.float32), frozen=jnp.zeros(4096, jnp.float32)
        )
    }
    Snapshot(path=mgr.path_for(1)).restore(dst)
    assert dst["m"]["w"].dtype == jnp.float32
    np.testing.assert_array_equal(
        np.asarray(dst["m"]["w"]),
        (np.arange(4096, dtype="float32") * 2).astype("bfloat16").astype("float32"),
    )
    np.testing.assert_array_equal(np.asarray(dst["m"]["frozen"]), np.ones(4096, "float32"))
