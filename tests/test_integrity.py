"""End-to-end integrity: CRC32C checksums recorded on save, verified on load.

Fault injection follows the reference's pattern (SURVEY.md §4.4) but at the
storage level: corrupt bytes on disk after a committed save, then assert the
restore fails loudly instead of returning corrupt tensors.
"""

from __future__ import annotations

import numpy as np
import pytest

import torchsnapshot_tpu._native as native_mod
from torchsnapshot_tpu import Snapshot, StateDict
from torchsnapshot_tpu._native import _crc32c_py, crc32c, native_available, scatter_copy
from torchsnapshot_tpu.integrity import IntegrityError, VERIFY_ENV_VAR
from torchsnapshot_tpu.manifest import SnapshotMetadata


# ------------------------------------------------------------------ crc32c

def test_crc32c_known_answer() -> None:
    # RFC 3720 test vector.
    assert crc32c(b"123456789") == 0xE3069283
    assert _crc32c_py(b"123456789") == 0xE3069283


def test_crc32c_chaining_and_empty() -> None:
    a, b = b"hello ", b"world"
    assert crc32c(b, crc32c(a)) == crc32c(a + b)
    assert crc32c(b"") == 0


def test_crc32c_native_matches_python() -> None:
    data = np.random.default_rng(0).integers(0, 256, 65537, np.uint8).tobytes()
    assert crc32c(data) == _crc32c_py(data)


def test_crc32c_3way_boundaries_and_chaining() -> None:
    """The hardware path switches to 3-way interleaved lanes at 24 KB
    (3 x kLane) with a GF(2) zero-shift recombine; pin bit-exactness right
    around the switch, across multi-block sizes, and when the incoming crc
    is a chained (nonzero) state entering the 3-way block loop."""
    rng = np.random.default_rng(1)
    for sz in (24575, 24576, 24577, 3 * 24576, 100_001):
        data = rng.integers(0, 256, sz, np.uint8).tobytes()
        assert crc32c(data) == _crc32c_py(data), sz
        # arbitrary split: the second call enters 3-way with nonzero state
        assert crc32c(data[999:], crc32c(data[:999])) == crc32c(data), sz


def test_crc32c_python_fallback_used_when_native_disabled(monkeypatch) -> None:
    monkeypatch.setattr(native_mod, "_lib", None)
    monkeypatch.setattr(native_mod, "_load_attempted", True)
    assert not native_available()
    assert crc32c(b"123456789") == 0xE3069283


# ------------------------------------------------------------- scatter copy

def test_scatter_copy_matches_slicing() -> None:
    rng = np.random.default_rng(1)
    src = rng.integers(0, 256, 4096, np.uint8).tobytes()
    regions = [(0, 100, 50), (60, 0, 60), (1000, 2000, 1024), (3000, 500, 7)]
    dst_native = bytearray(4096)
    scatter_copy(dst_native, src, regions)
    dst_py = bytearray(4096)
    for d, s, n in regions:
        dst_py[d : d + n] = src[s : s + n]
    assert dst_native == dst_py


def test_gather_copy_packs_sources() -> None:
    from torchsnapshot_tpu._native import gather_copy

    rng = np.random.default_rng(2)
    srcs = [rng.integers(0, 256, n, np.uint8).tobytes() for n in (100, 7, 512, 64, 1)]
    offsets = [0, 100, 120, 700, 800]
    dst = bytearray(1024)
    gather_copy(dst, list(zip(offsets, srcs)))
    for off, src in zip(offsets, srcs):
        assert bytes(dst[off : off + len(src)]) == src


def test_gather_copy_bounds_checked() -> None:
    from torchsnapshot_tpu._native import gather_copy

    if not native_available():
        pytest.skip("bounds check lives on the native path")
    with pytest.raises(ValueError, match="out of bounds"):
        gather_copy(bytearray(10), [(0, b"123")] * 4 + [(8, b"12345")])


def test_scatter_copy_bounds_checked() -> None:
    if not native_available():
        pytest.skip("bounds check lives on the native path")
    with pytest.raises(ValueError, match="out of bounds"):
        scatter_copy(bytearray(10), b"x" * 10, [(0, 0, 5)] * 4 + [(8, 0, 5)])


# ------------------------------------------------- snapshot-level integrity

def _entry_checksums(snapshot: Snapshot):
    out = {}
    for path, entry in snapshot.get_manifest().items():
        subs = [entry]
        for part in list(getattr(entry, "chunks", [])) + list(
            getattr(entry, "shards", [])
        ):
            subs.append(part.array)
        for sub in subs:
            checksum = getattr(sub, "checksum", None)
            if checksum is not None:
                out[f"{path}@{sub.location}" if sub is not entry else path] = checksum
    return out


def test_checksums_recorded_on_save(tmp_path) -> None:
    state = StateDict(
        arr=np.arange(1000, dtype=np.float32),
        obj={"nested": [1, 2, 3]},
    )
    snap = Snapshot.take(str(tmp_path / "s"), {"app": state})
    checksums = _entry_checksums(snap)
    assert any("arr" in p for p in checksums)
    # Native builds record crc32c; the no-toolchain fallback records
    # stdlib crc32 under its own algorithm tag.
    from torchsnapshot_tpu._native import native_available

    expected = "crc32c:" if native_available() else "crc32:"
    assert all(c.startswith(expected) for c in checksums.values())
    # Checksums survive the YAML round trip.
    meta = SnapshotMetadata.from_yaml(
        (tmp_path / "s" / ".snapshot_metadata").read_text()
    )
    round_tripped = []
    for e in meta.manifest.values():
        for part in list(getattr(e, "chunks", [])) + list(getattr(e, "shards", [])):
            if part.array.checksum:
                round_tripped.append(part.array.checksum)
        if getattr(e, "checksum", None):
            round_tripped.append(e.checksum)
    assert round_tripped


def _corrupt_one_file(root, match: str) -> str:
    """Flip a byte in the first payload file whose path contains ``match``."""
    for f in sorted(root.rglob("*")):
        if f.is_file() and match in str(f) and ".snapshot_metadata" not in f.name:
            data = bytearray(f.read_bytes())
            data[len(data) // 2] ^= 0xFF
            f.write_bytes(bytes(data))
            return str(f)
    raise AssertionError(f"no payload file matching {match}")


def test_corrupt_array_detected_on_restore(tmp_path) -> None:
    state = StateDict(w=np.random.default_rng(0).standard_normal(500))
    Snapshot.take(str(tmp_path / "s"), {"app": state})
    _corrupt_one_file(tmp_path / "s", "w")
    dst = StateDict(w=np.zeros(500))
    with pytest.raises(IntegrityError, match="checksum mismatch"):
        Snapshot(str(tmp_path / "s")).restore({"app": dst})


def test_corrupt_object_detected_on_restore(tmp_path) -> None:
    state = StateDict(blob=set(range(100)))  # sets pickle as ObjectEntry
    Snapshot.take(str(tmp_path / "s"), {"app": state})
    _corrupt_one_file(tmp_path / "s", "blob")
    dst = StateDict(blob=None)
    with pytest.raises(IntegrityError, match="checksum mismatch"):
        Snapshot(str(tmp_path / "s")).restore({"app": dst})


def test_truncation_detected_on_restore(tmp_path) -> None:
    state = StateDict(w=np.arange(4096, dtype=np.float64))
    Snapshot.take(str(tmp_path / "s"), {"app": state})
    for f in sorted((tmp_path / "s").rglob("*")):
        if f.is_file() and "w" in str(f) and ".snapshot_metadata" not in f.name:
            f.write_bytes(f.read_bytes()[:-512])
            break
    dst = StateDict(w=np.zeros(4096))
    with pytest.raises(Exception):  # IntegrityError (or size mismatch)
        Snapshot(str(tmp_path / "s")).restore({"app": dst})


def test_verification_can_be_disabled(tmp_path, monkeypatch) -> None:
    state = StateDict(w=np.arange(256, dtype=np.float32))
    Snapshot.take(str(tmp_path / "s"), {"app": state})
    _corrupt_one_file(tmp_path / "s", "w")
    monkeypatch.setenv(VERIFY_ENV_VAR, "0")
    dst = StateDict(w=np.zeros(256, dtype=np.float32))
    Snapshot(str(tmp_path / "s")).restore({"app": dst})  # no raise
    assert not np.array_equal(dst["w"], state["w"])  # silently corrupt


def test_checksum_recording_can_be_disabled(tmp_path, monkeypatch) -> None:
    from torchsnapshot_tpu.integrity import CHECKSUM_ENV_VAR

    monkeypatch.setenv(CHECKSUM_ENV_VAR, "0")
    state = StateDict(w=np.arange(256, dtype=np.float32))
    snap = Snapshot.take(str(tmp_path / "s"), {"app": state})
    assert not _entry_checksums(snap)
    # Restores of checksum-less snapshots still work (backward compat).
    dst = StateDict(w=np.zeros(256, dtype=np.float32))
    snap.restore({"app": dst})
    np.testing.assert_array_equal(dst["w"], state["w"])


def test_sharded_array_checksums(tmp_path) -> None:
    import jax
    import jax.numpy as jnp
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    mesh = Mesh(np.array(jax.devices()[:4]).reshape(4), ("x",))
    arr = jax.device_put(
        jnp.arange(64 * 8, dtype=jnp.float32).reshape(64, 8),
        NamedSharding(mesh, P("x", None)),
    )
    Snapshot.take(str(tmp_path / "s"), {"app": StateDict(arr=arr)})
    # every shard sub-entry carries a checksum
    snap = Snapshot(str(tmp_path / "s"))
    sharded = [
        e for e in snap.get_manifest().values()
        if getattr(e, "shards", None)
    ]
    assert sharded
    assert all(s.array.checksum for e in sharded for s in e.shards)
    # corrupt one shard file -> restore fails
    _corrupt_one_file(tmp_path / "s", "arr")
    dst = jax.device_put(jnp.zeros((64, 8)), NamedSharding(mesh, P("x", None)))
    with pytest.raises(IntegrityError):
        snap.restore({"app": StateDict(arr=dst)})


def test_copy_crc32c_matches_crc32c():
    """Fused copy+CRC must produce byte-identical copies and the same
    checksum as the separate crc32c over any size/alignment (block
    boundaries at 256 KB inside the native loop)."""
    import numpy as np

    from torchsnapshot_tpu._native import copy_crc32c, crc32c, native_available

    if not native_available():
        import pytest

        pytest.skip("native extension unavailable")
    rng = np.random.default_rng(0)
    for n in (0, 1, 255, 1 << 18, (1 << 18) + 7, 3_000_001):
        src = rng.integers(0, 255, n, np.uint8)
        dst = np.full(n, 0xAA, np.uint8)
        crc = copy_crc32c(dst, src)
        assert crc == crc32c(src)
        assert np.array_equal(dst, src)


def test_staging_pool_recycles_on_gc():
    import gc

    import numpy as np

    from torchsnapshot_tpu.io_preparers.array import _StagingPool

    pool = _StagingPool(limit_bytes=1 << 20)
    buf = pool.get(4096)
    base_ptr = buf.ctypes.data
    buf[0] = 7
    del buf
    gc.collect()
    again = pool.get(4096)
    assert again.ctypes.data == base_ptr  # same slab came back
    # over-limit slabs are dropped, not pooled
    big = pool.get(2 << 20)
    big_ptr = big.ctypes.data
    del big
    gc.collect()
    assert pool._free_bytes <= 1 << 20


def test_staging_pool_derived_view_pins_slab():
    """A numpy-level slice of a pooled buffer must keep the slab checked
    out even after the originally-returned array dies — otherwise the
    slab is recycled and handed to a new owner while the derived view
    still aliases it (silent checkpoint corruption)."""
    import gc

    import numpy as np

    from torchsnapshot_tpu.io_preparers.array import _StagingPool

    pool = _StagingPool(limit_bytes=1 << 20)
    buf = pool.get(4096)
    buf[:] = 7
    view = buf[10:20]  # numpy slice, NOT a memoryview
    ptr = buf.ctypes.data
    del buf
    gc.collect()
    # The slab must NOT come back while `view` aliases it.
    other = pool.get(4096)
    assert other.ctypes.data != ptr
    other[:] = 99
    assert np.all(view == 7)  # new owner's writes are not visible
    del view, other
    gc.collect()
    # With all references dead, the slab finally recycles.
    free_ptrs = {s.ctypes.data for slabs in pool._free.values() for s in slabs}
    assert ptr in free_ptrs


def test_async_take_fused_checksum_verifies_on_restore(tmp_path):
    """async_take stages through the fused copy+CRC path (consistency
    copy + checksum in one pass); the recorded checksums must verify on
    restore and the data round-trip bit-exactly."""
    import numpy as np

    from torchsnapshot_tpu import Snapshot, StateDict

    state = StateDict(
        a=np.arange(100_000, dtype=np.float32),
        b=np.arange(33_333, dtype=np.int64),
    )
    pending = Snapshot.async_take(str(tmp_path / "s"), {"app": state})
    snap = pending.wait()
    meta = snap.metadata
    from torchsnapshot_tpu.cli import _entry_payloads

    checksums = [
        checksum
        for e in meta.manifest.values()
        for _, _, checksum, _, _ in _entry_payloads(e)
        if checksum is not None
    ]
    assert checksums, "staging must record checksums"
    assert all(c.startswith(("crc32c:", "crc32:")) for c in checksums)
    dst = StateDict(
        a=np.zeros(100_000, np.float32), b=np.zeros(33_333, np.int64)
    )
    Snapshot(str(tmp_path / "s")).restore({"app": dst})  # verifies CRCs
    np.testing.assert_array_equal(dst["a"], state["a"])
    np.testing.assert_array_equal(dst["b"], state["b"])
