"""Native I/O fast path: pinned slab allocator, io_uring engine, the fs
plugin's native stream paths, and the IOGovernor election (ISSUE 9).

Four layers, mirroring the subsystem's seams:

- **Slab allocator / staging pool**: page-aligned, pre-faulted-at-
  construction slabs; GC-driven recycling with derived-view pinning on
  every interpreter (the ctypes holder, not PEP 688); telemetry gauges.
- **Engine**: submit/wait/drain semantics, EOF taxonomy, the
  buffer-pin contract (a pooled slab is never recycled while its SQE
  may be in flight).
- **fs plugin**: native streamed writes/reads are byte- and
  checksum-identical to the Python path, atomic on mid-stream failure,
  and drilled through the ``fs.native_*`` fault sites.
- **Election**: env modes, the governor's measured-rate gates, silent
  degradation when the probe fails, and the recorded election event.
"""

import asyncio
import gc
import os

import numpy as np
import pytest

from torchsnapshot_tpu import faultinject, native_io
from torchsnapshot_tpu import _native
from torchsnapshot_tpu.io_types import ReadIO, WriteStream
from torchsnapshot_tpu.io_preparers.array import (
    _NATIVE_SLAB_MIN_BYTES,
    _StagingPool,
    pooled_buffer,
)
from torchsnapshot_tpu.scheduler import IOGovernor
from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

native_present = pytest.mark.skipif(
    not _native.native_available(), reason="native extension unavailable"
)
uring_present = pytest.mark.skipif(
    native_io.engine_kind() != "uring", reason="io_uring unavailable"
)


@pytest.fixture
def loop():
    loop = asyncio.new_event_loop()
    yield loop
    loop.close()


async def _chunks_of(payload: bytes, n: int):
    for lo in range(0, len(payload), n):
        yield payload[lo : lo + n]


async def _collect(stream) -> bytes:
    out = bytearray()
    async for chunk in stream.chunks:
        out += bytes(memoryview(chunk).cast("B"))
    return bytes(out)


# ------------------------------------------------------- slab allocator


@native_present
def test_slab_alloc_page_aligned_and_writable():
    out = _native.slab_alloc(1 << 20)
    assert out is not None
    addr, caps = out
    try:
        assert addr % 4096 == 0
        assert caps & _native.SLAB_PREFAULT  # pre-faulted at construction
        view = np.frombuffer(
            (np.ctypeslib.ctypes.c_ubyte * (1 << 20)).from_address(addr),
            np.uint8,
        )
        view[:] = 7
        assert int(view[-1]) == 7
    finally:
        _native.slab_free(addr, 1 << 20)


@native_present
def test_pool_native_recycles_and_aligns():
    pool = _StagingPool(limit_bytes=1 << 22)
    buf = pool.get(1 << 20)
    assert buf.ctypes.data % 4096 == 0  # aligned for O_DIRECT/io_uring
    ptr = buf.ctypes.data
    del buf
    gc.collect()
    again = pool.get(1 << 20)
    assert again.ctypes.data == ptr  # same pinned slab came back
    # Eviction past the limit frees the mapping instead of pooling it.
    big = pool.get(1 << 22)
    del big, again
    gc.collect()
    assert pool._free_bytes <= 1 << 22


@native_present
def test_pool_native_derived_view_pins_slab():
    pool = _StagingPool(limit_bytes=1 << 22)
    buf = pool.get(1 << 20)
    buf[:] = 7
    view = buf[10:20]
    ptr = buf.ctypes.data
    del buf
    gc.collect()
    other = pool.get(1 << 20)
    assert other.ctypes.data != ptr  # slab NOT recycled while aliased
    other[:] = 99
    assert np.all(view == 7)
    del view, other
    gc.collect()
    free_ptrs = {s.ctypes.data for slabs in pool._free.values() for s in slabs}
    assert ptr in free_ptrs  # recycled once every reference died


@native_present
def test_pool_degrade_frees_native_slabs(monkeypatch):
    """A mid-run allocation failure degrades the pool to the Python
    path; pooled native slabs must be munmap'd at that transition (and
    late returners freed), never inherited by _get_py — whose eviction
    would drop the pinned mapping with no munmap."""
    pool = _StagingPool(limit_bytes=1 << 24)
    a = pool.get(1 << 20)
    held = pool.get(1 << 20)  # still checked out across the degrade
    del a
    gc.collect()
    assert pool._free_bytes == 1 << 20
    monkeypatch.setattr("torchsnapshot_tpu._native.slab_view", lambda n: None)
    b = pool.get(2 << 20)  # allocation fails -> degrade for good
    assert pool._native is False
    assert all(n < _NATIVE_SLAB_MIN_BYTES for n in pool._free)  # drained
    b[:] = 1  # the fallback buffer is an ordinary working buffer
    del held
    gc.collect()  # the late returner is freed, not pooled
    assert all(n < _NATIVE_SLAB_MIN_BYTES for n in pool._free)


def test_pool_tiny_buffers_skip_native_path():
    pool = _StagingPool(limit_bytes=1 << 22)
    small = pool.get(_NATIVE_SLAB_MIN_BYTES - 1)
    small[:] = 3  # writable, correct size — the whole contract for tiny bufs
    assert small.nbytes == _NATIVE_SLAB_MIN_BYTES - 1


def test_pool_python_fallback_same_surface():
    """With native slabs unavailable the pool must keep the identical
    call surface and buffer semantics (writable exact-size uint8),
    recycling when the interpreter allows it and degrading to fresh
    allocations when not — never erroring."""
    pool = _StagingPool(limit_bytes=1 << 22)
    pool._native = False  # simulate a build-absent host
    buf = pool.get(1 << 16)
    assert buf.dtype == np.uint8 and buf.nbytes == 1 << 16
    buf[:] = 42
    assert int(buf[-1]) == 42
    assert pool.prewarm([1 << 16]) >= 0  # never raises


@native_present
def test_pool_prewarm_allocates_prefaulted_slabs():
    pool = _StagingPool(limit_bytes=1 << 24)
    warmed = pool.prewarm([1 << 20, 1 << 20, 1 << 16])
    assert warmed == (1 << 20) * 2 + (1 << 16)
    assert pool.prewarm([1 << 20, 1 << 20]) == 0  # already pooled
    # The warmed slabs are exactly what get() hands out.
    ptrs = {s.ctypes.data for slabs in pool._free.values() for s in slabs}
    got = pool.get(1 << 20)
    assert got.ctypes.data in ptrs


@native_present
def test_pool_telemetry_gauges(monkeypatch):
    from torchsnapshot_tpu import telemetry

    telemetry.set_enabled(True)
    try:
        telemetry.reset()
        pool = _StagingPool(limit_bytes=1 << 22)
        a = pool.get(1 << 20)  # miss
        del a
        gc.collect()
        b = pool.get(1 << 20)  # hit
        counters = telemetry.counters()
        assert counters.get("staging_pool_misses", 0) >= 1
        assert counters.get("staging_pool_hits", 0) >= 1
        gauges = telemetry.gauges()
        assert gauges.get("staging_pool_outstanding_bytes") == 1 << 20
        del b
    finally:
        telemetry.set_enabled(False)
        telemetry.reset()


# --------------------------------------------------------------- engine


@uring_present
def test_engine_write_read_roundtrip(tmp_path):
    eng = native_io.open_engine()
    assert isinstance(eng, native_io.UringEngine)
    path = str(tmp_path / "f")
    fd = os.open(path, os.O_RDWR | os.O_CREAT, 0o600)
    try:
        payload = np.frombuffer(os.urandom(1 << 18), np.uint8).copy()
        slots = [
            eng.submit_pwrite(fd, payload[lo : lo + (1 << 16)], lo)
            for lo in range(0, 1 << 18, 1 << 16)
        ]
        eng.drain()
        back = np.zeros(1 << 18, np.uint8)
        slot = eng.submit_pread(fd, back, 0)
        eng.wait(slot)
        assert np.array_equal(back, payload)
        assert len(slots) == 4
    finally:
        eng.close()
        os.close(fd)


@uring_present
def test_engine_short_read_is_eoferror(tmp_path):
    path = str(tmp_path / "short")
    with open(path, "wb") as f:
        f.write(b"x" * 100)
    eng = native_io.open_engine()
    fd = os.open(path, os.O_RDONLY)
    try:
        buf = np.zeros(4096, np.uint8)
        slot = eng.submit_pread(fd, buf, 0)
        with pytest.raises(EOFError):
            eng.wait(slot, path)
    finally:
        eng.close()
        os.close(fd)


@uring_present
def test_engine_error_propagates_from_drain(tmp_path):
    path = str(tmp_path / "ro")
    with open(path, "wb") as f:
        f.write(b"y" * 10)
    eng = native_io.open_engine()
    fd = os.open(path, os.O_RDONLY)  # write to an O_RDONLY fd must fail
    try:
        eng.submit_pwrite(fd, np.zeros(64, np.uint8), 0)
        with pytest.raises(OSError):
            eng.drain()
    finally:
        eng.close()
        os.close(fd)


@uring_present
def test_engine_pins_pooled_buffer_until_reaped(tmp_path):
    """The satellite-3 lifetime contract: a pooled slab handed to the
    engine is NEVER recycled while its SQE may be in flight — even if
    the Python side drops every reference before waiting."""
    from torchsnapshot_tpu.io_preparers.array import _staging_pool

    path = str(tmp_path / "pin")
    with open(path, "wb") as f:
        f.write(os.urandom(1 << 20))
    eng = native_io.open_engine()
    fd = os.open(path, os.O_RDONLY)
    try:
        # A deliberately odd size: the process-global pool is exact-size
        # keyed, so this test can never donate a slab that other tests'
        # (or the write path's) round sizes would silently inherit.
        size = (1 << 20) - 8192
        buf = pooled_buffer(size)
        ptr = buf.ctypes.data
        slot = eng.submit_pread(fd, buf, 0)
        del buf  # the engine's pin must now be the only thing holding it
        gc.collect()
        fresh = _staging_pool.get(size)
        assert fresh.ctypes.data != ptr  # in-flight slab NOT handed out
        eng.wait(slot)
        gc.collect()
        recycled = _staging_pool.get(size)
        assert recycled.ctypes.data == ptr  # reaped slab recycles
        del fresh, recycled
    finally:
        eng.close()
        os.close(fd)


# ------------------------------------------------------------ fs plugin


@uring_present
def test_fs_native_stream_equals_python_stream(tmp_path, loop, monkeypatch):
    payload = os.urandom((1 << 20) + 12345)  # unaligned tail
    plugin = FSStoragePlugin(root=str(tmp_path))

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "never")
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(
                path="python", nbytes=len(payload),
                chunks=_chunks_of(payload, 100_000),
            )
        )
    )
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(
                path="native", nbytes=len(payload),
                chunks=_chunks_of(payload, 100_000),
            )
        )
    )
    assert (tmp_path / "native").read_bytes() == (tmp_path / "python").read_bytes()

    # Native streamed reads produce the identical byte stream too.
    stream = loop.run_until_complete(
        plugin.read_stream(ReadIO(path="native"), 100_000)
    )
    assert loop.run_until_complete(_collect(stream)) == payload
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "never")
    stream = loop.run_until_complete(
        plugin.read_stream(ReadIO(path="native"), 100_000)
    )
    assert loop.run_until_complete(_collect(stream)) == payload


@uring_present
def test_fs_native_ranged_read_stream(tmp_path, loop, monkeypatch):
    payload = os.urandom(1 << 20)
    (tmp_path / "r").write_bytes(payload)
    plugin = FSStoragePlugin(root=str(tmp_path))
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    stream = loop.run_until_complete(
        plugin.read_stream(
            ReadIO(path="r", byte_range=(1000, 700_000)), 65_536
        )
    )
    assert loop.run_until_complete(_collect(stream)) == payload[1000:700_000]


@uring_present
def test_fs_native_midstream_failure_atomic(tmp_path, loop, monkeypatch):
    """An injected failure at the native pwrite site aborts the stream
    with NO final object and NO temp litter — the same atomicity the
    Python path pins."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1 << 20)
    faultinject.configure("fs.native_pwrite@2=permanent")
    try:
        with pytest.raises(OSError):
            loop.run_until_complete(
                plugin.write_stream(
                    WriteStream(
                        path="obj", nbytes=len(payload),
                        chunks=_chunks_of(payload, 100_000),
                    )
                )
            )
    finally:
        faultinject.disable()
    assert not (tmp_path / "obj").exists()
    assert not list(tmp_path.glob("*.tmp.*"))
    assert faultinject.hits() == {}  # disabled resets


@uring_present
def test_fs_native_truncate_fault_detected_as_short_write(
    tmp_path, loop, monkeypatch
):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1 << 20)
    faultinject.configure("fs.native_pwrite@3=truncate:0.5")
    try:
        with pytest.raises(IOError):
            loop.run_until_complete(
                plugin.write_stream(
                    WriteStream(
                        path="obj", nbytes=len(payload),
                        chunks=_chunks_of(payload, 100_000),
                    )
                )
            )
    finally:
        faultinject.disable()
    assert not (tmp_path / "obj").exists()


@uring_present
def test_fs_native_pread_corrupt_drills_verification(
    tmp_path, loop, monkeypatch
):
    """A corrupt fault at the native pread site must surface through the
    normal read-side taxonomy: the stream yields mutated bytes, and the
    consumer's chained CRC (exercised end-to-end elsewhere) is what
    catches it — here we pin that the site actually fires and mutates."""
    payload = os.urandom(1 << 20)
    (tmp_path / "r").write_bytes(payload)
    plugin = FSStoragePlugin(root=str(tmp_path))
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    faultinject.configure("fs.native_pread@1=corrupt;seed=3")
    try:
        stream = loop.run_until_complete(
            plugin.read_stream(ReadIO(path="r"), 65_536)
        )
        got = loop.run_until_complete(_collect(stream))
    finally:
        faultinject.disable()
    assert len(got) == len(payload)
    assert got != payload  # exactly one flipped byte
    assert sum(a != b for a, b in zip(got, payload)) == 1


# -------------------------------------------------------------- election


def test_native_io_mode_parser(monkeypatch):
    for raw, want in [
        ("never", "never"), ("0", "never"), ("off", "never"),
        ("always", "always"), ("1", "always"), ("force", "always"),
        ("auto", "auto"), ("", "auto"), ("garbage", "auto"),
    ]:
        monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", raw)
        assert native_io.native_io_mode() == want, raw


def test_elect_never_short_circuits(monkeypatch):
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "never")
    assert native_io.maybe_engine("write", "FSStoragePlugin") is None


def test_elect_degrades_silently_without_engine(monkeypatch):
    """Build-absent / ENOSYS / EPERM all collapse to engine_kind() None;
    election then returns False even under `always` — the Python path
    takes over with no error surfaced."""
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    monkeypatch.setattr(native_io, "_probe_done", True)
    monkeypatch.setattr(native_io, "_probe_kind", None)
    assert native_io.elect("write", "FSStoragePlugin") is False
    assert native_io.maybe_engine("write", "FSStoragePlugin") is None


def test_governor_native_write_gate():
    governor = IOGovernor()
    # Unmeasured: optimistic (the streaming-writes precedent).
    assert governor.should_native_io("FSStoragePlugin", op="write")
    governor.record_write("FSStoragePlugin", 1 << 30, 1.0)
    assert governor.should_native_io("FSStoragePlugin", op="write")
    # Native measured clearly slower than the pipeline without it: depose.
    governor.record_write("FSStoragePlugin.native", 1 << 30, 2.0)
    assert not governor.should_native_io("FSStoragePlugin", op="write")
    # Native at parity: stays elected (hysteresis margin).
    governor.record_write("FSStoragePlugin.native", 1 << 30, 0.25)
    assert governor.should_native_io("FSStoragePlugin", op="write")


def test_governor_native_read_gate_uses_latency_knee():
    governor = IOGovernor()
    # No measurement: status-quo Python path (unlike the write side).
    assert not governor.should_native_io("FSStoragePlugin", op="read")
    # memcpy-speed local reads: queue depth buys nothing — stay Python.
    governor.record_read("FSStoragePlugin", 4 << 30, 1.0)
    assert not governor.should_native_io("FSStoragePlugin", op="read")
    # Latency-bound storage: elect.
    governor_slow = IOGovernor()
    governor_slow.record_read("FSStoragePlugin", 50 << 20, 1.0)
    assert governor_slow.should_native_io("FSStoragePlugin", op="read")
    # ...unless the native engine itself measured clearly worse there.
    governor_slow.record_read("FSStoragePlugin.native", 10 << 20, 1.0)
    assert not governor_slow.should_native_io("FSStoragePlugin", op="read")


@uring_present
def test_election_recorded_on_flight_ring(tmp_path, loop, monkeypatch):
    from torchsnapshot_tpu.telemetry import flightrec

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    native_io._election_seen.clear()
    plugin = FSStoragePlugin(root=str(tmp_path))
    payload = os.urandom(1 << 18)
    loop.run_until_complete(
        plugin.write_stream(
            WriteStream(
                path="e", nbytes=len(payload),
                chunks=_chunks_of(payload, 1 << 16),
            )
        )
    )
    events = [
        args
        for (_seq, _t, ev, args) in flightrec.snapshot_ring()
        if ev == "governor.elect" and (args or {}).get("site") == "native_io"
    ]
    assert events, "native_io election must land on the flight ring"
    last = events[-1]
    assert last["elected"] is True and last["engine"] == "uring"


@uring_present
def test_native_end_to_end_snapshot_roundtrip(tmp_path, monkeypatch):
    """A forced-native streamed take records the same checksums the
    Python path would and restores bit-exactly (streamed==buffered
    equivalence at the Snapshot level)."""
    from torchsnapshot_tpu import Snapshot, StateDict

    monkeypatch.setenv("TORCHSNAPSHOT_TPU_NATIVE_IO", "always")
    monkeypatch.setenv("TORCHSNAPSHOT_TPU_SUB_CHUNK_BYTES", str(1 << 18))
    rng = np.random.default_rng(7)
    state = {"m": StateDict(w=rng.standard_normal(500_000).astype(np.float32))}
    Snapshot.take(str(tmp_path / "s"), state)
    dst = {"m": StateDict(w=np.zeros(500_000, np.float32))}
    Snapshot(str(tmp_path / "s")).restore(dst)
    assert np.array_equal(dst["m"]["w"], state["m"]["w"])
    # The recorded checksum algorithm matches the Python streamed path.
    meta = Snapshot(str(tmp_path / "s")).metadata

    def _array_entries(entry):
        for shard in getattr(entry, "chunks", []) + getattr(entry, "shards", []):
            yield shard.array
        if getattr(entry, "checksum", None) is not None:
            yield entry

    checksums = [
        arr.checksum
        for e in meta.manifest.values()
        for arr in _array_entries(e)
        if getattr(arr, "checksum", None) is not None
    ]
    assert checksums and all(c.startswith("crc32c:") for c in checksums)
