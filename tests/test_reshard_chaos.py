"""Planned-reshard chaos drills (ISSUE 12): every peer failure mode
degrades the affected entry to a direct storage read — counted, prompt,
bit-exact — and never a hang or a torn restore.

All drills run the same world-2 pure layout change (rows saved under
``P("x", None)``, restored as columns under ``P(None, "x")``) so BOTH
ranks own one planned unit and receive one:

- corrupt / truncate the bundle as it leaves the owner
  (``reshard.peer_xfer`` fault site): the receiver's CRC/length check
  fires BEFORE any scatter, one counted fallback re-reads storage;
- delay: slides latency under the coop timeout — no fallback, the
  planned path completes;
- owner peer-channel death mid-transfer: receivers see the drop, mark
  the source dead, and direct-read its units (death-driven, not
  timeout-driven);
- SIGKILL the non-store-host rank at its forwarding boundary: the
  survivor's data is complete and bit-exact before the world tears
  down, and its abort is bounded by the barrier timeout.
"""

from __future__ import annotations

import os
import time

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

from tests.test_reshard_restore import (
    _assert_local_shards_equal,
    _init_jax_dist,
    _install_read_counter,
    _make,
    _payload,
    _vals,
)

pytestmark = [pytest.mark.multiprocess]


def _chaos_worker(rank, world_size, root, port, plan_by_rank):
    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, faultinject, telemetry

    telemetry.refresh_from_env()
    arr = _make(jax, _vals(), P("x", None))
    Snapshot.take(root, {"model": StateDict(w=arr)})

    counts = _install_read_counter()
    faultinject.configure(plan_by_rank.get(rank))
    try:
        dst = {
            "model": StateDict(
                w=_make(
                    jax, np.zeros(_vals().shape, np.float32), P(None, "x")
                )
            )
        }
        Snapshot(root).restore(dst)
    finally:
        faultinject.disable()
    _assert_local_shards_equal(dst["model"]["w"], _vals())
    c = telemetry.counters()
    return {
        "payload_read": sum(counts.values()),
        "from_peers": int(c.get("bytes_resharded_from_peers", 0)),
        "fallbacks": int(c.get("fanout_fallbacks", 0)),
    }


def test_corrupt_bundle_falls_back_bit_exact(tmp_path) -> None:
    """Both owners corrupt their first bundle: both receivers reject it
    at the CRC (before any scatter) and re-read storage — one counted
    fallback each, bit-exact."""
    results = run_with_subprocesses(
        _chaos_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        {0: "reshard.peer_xfer@1=corrupt;seed=5",
         1: "reshard.peer_xfer@1=corrupt;seed=6"},
        timeout=240.0,
    )
    for rank, r in results.items():
        assert r["fallbacks"] == 1, (rank, results)
        assert r["from_peers"] == 0, (rank, results)
    # Each rank read its owned shard plus the fallback re-read of its
    # peer's shard: 2x the payload fleet-wide, but never a hang.
    fleet = sum(r["payload_read"] for r in results.values())
    assert fleet >= 1.8 * _payload(), results


def test_truncated_bundle_falls_back_one_sided(tmp_path) -> None:
    """Only rank 0 truncates its outgoing bundle: rank 1 takes the
    counted fallback; rank 0's own receive still arrives via the wire."""
    results = run_with_subprocesses(
        _chaos_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        {0: "reshard.peer_xfer@1=truncate:0.3"},
        timeout=240.0,
    )
    assert results[1]["fallbacks"] == 1, results
    assert results[0]["fallbacks"] == 0, results
    assert results[0]["from_peers"] > 0, results


def test_delayed_bundle_completes_planned(tmp_path) -> None:
    """A delayed bundle (within the coop timeout) is NOT a failure:
    the planned path completes on both ranks with zero fallbacks."""
    results = run_with_subprocesses(
        _chaos_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        {0: "reshard.peer_xfer@1=delay:1.5"},
        timeout=240.0,
    )
    for rank, r in results.items():
        assert r["fallbacks"] == 0, (rank, results)
        assert r["from_peers"] > 0, (rank, results)


def _owner_death_worker(rank, world_size, root, port):
    """Rank 0 closes every outbound peer socket at its first forwarded
    reshard frame — data-plane death while its own restore (and the
    collectives) stay alive."""
    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    telemetry.refresh_from_env()
    arr = _make(jax, _vals(), P("x", None))
    Snapshot.take(root, {"model": StateDict(w=arr)})

    if rank == 0:
        from torchsnapshot_tpu import fanout

        orig = fanout.CoopRestoreSession._send_one

        def dying_send(self, r, header, payload, _orig=orig):
            if str(header.get("key", "")).startswith("reshard|"):
                for sock, _lock in self._out.values():
                    try:
                        sock.close()
                    except OSError:
                        pass
            _orig(self, r, header, payload)

        fanout.CoopRestoreSession._send_one = dying_send

    counts = _install_read_counter()
    dst = {
        "model": StateDict(
            w=_make(jax, np.zeros(_vals().shape, np.float32), P(None, "x"))
        )
    }
    Snapshot(root).restore(dst)
    _assert_local_shards_equal(dst["model"]["w"], _vals())
    c = telemetry.counters()
    return {
        "payload_read": sum(counts.values()),
        "fallbacks": int(c.get("fanout_fallbacks", 0)),
    }


def test_owner_channel_death_falls_back_bit_exact(tmp_path) -> None:
    results = run_with_subprocesses(
        _owner_death_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        timeout=240.0,
    )
    # Rank 1 lost rank 0's bundle mid-wire and re-read storage.
    assert results[1]["fallbacks"] >= 1, results
    assert results[1]["payload_read"] > 0, results


def _sigkill_worker(rank, world_size, root, port):
    """The w2 SIGKILL schedule: rank 1 (NOT the store host) dies at its
    forwarding boundary. The survivor's entry degrades to storage and
    its data is bit-exact; the torn world aborts within the barrier
    timeout instead of hanging."""
    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "20"
    os.environ["TORCHSNAPSHOT_TPU_BARRIER_TIMEOUT"] = "20"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, faultinject, telemetry

    telemetry.refresh_from_env()
    arr = _make(jax, _vals(), P("x", None))
    Snapshot.take(root, {"model": StateDict(w=arr)})

    if rank == 1:
        faultinject.configure("reshard.peer_xfer@1=kill")
    dst = {
        "model": StateDict(
            w=_make(jax, np.zeros(_vals().shape, np.float32), P(None, "x"))
        )
    }
    t0 = time.monotonic()
    try:
        Snapshot(root).restore(dst)
        status = "completed"
    except BaseException as e:  # noqa: B036 - the torn-world abort
        status = f"aborted:{type(e).__name__}"
    elapsed = time.monotonic() - t0
    # Whatever the collective outcome, the survivor's OWN data landed
    # complete before the teardown: scatter ran at entry execution, the
    # abort only fires at the post-key barrier.
    _assert_local_shards_equal(dst["model"]["w"], _vals())
    # Rank 1 can never join the launcher's exit barrier (it is dead by
    # design); the survivor satisfies it on the dead rank's behalf so
    # the drill ends when the abort does, not 60s later.
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    pg = get_default_pg()
    if pg is not None and pg.store is not None:
        pg.store.set("__exit__/done", b"1")
    c = telemetry.counters()
    return {
        "status": status,
        "elapsed": elapsed,
        "fallbacks": int(c.get("fanout_fallbacks", 0)),
    }


def test_sigkill_owner_mid_transfer(tmp_path) -> None:
    results = run_with_subprocesses(
        _sigkill_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        timeout=240.0, expect_dead=(1,),
    )
    assert set(results) == {0}, results
    r = results[0]
    # The survivor fell back for the dead owner's unit (death-driven),
    # kept bit-exact data (asserted in-worker), and aborted boundedly.
    assert r["fallbacks"] >= 1, results
    assert r["elapsed"] < 120.0, results
