"""End-to-end planned resharding (ISSUE 12): real ``jax.distributed``
worlds, real peer channel, real storage.

The acceptance drill: save at world 2 under tp2 row-parallel
(``P("x", None)``), restore at world 4 under column-parallel
(``P(None, "x")``) — a pure layout change where EVERY saved shard
overlaps EVERY destination rank. Direct restore reads each shard 4x
fleet-wide; the planned path must read each shard ONCE (>= 3x
reduction), move minimal region bundles over the peer channel, and stay
bit-exact either way.

Also pinned here: the election rides exactly ONE all-gather (the
4-tuple shared with the preverify/coop votes — referenced by name from
snapshot.py's ``_group_read_reqs`` docstring), and env skew (one rank
``never``) degrades the fleet to direct reads without a hang.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import _find_free_port, run_with_subprocesses

pytestmark = [pytest.mark.multiprocess]

ROWS, COLS = 256, 64  # divisible by 2 and 4 along both dims (64 KB fp32)


def _vals() -> np.ndarray:
    return np.arange(ROWS * COLS, dtype=np.float32).reshape(ROWS, COLS)


def _payload() -> int:
    return ROWS * COLS * 4


def _init_jax_dist(rank: int, world_size: int, port: int):
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["XLA_FLAGS"] = re.sub(
        r"--xla_force_host_platform_device_count=\d+",
        "",
        os.environ.get("XLA_FLAGS", ""),
    )
    import jax

    jax.config.update("jax_platforms", "cpu")
    jax.distributed.initialize(
        coordinator_address=f"localhost:{port}",
        num_processes=world_size,
        process_id=rank,
    )
    return jax


def _make(jax, values: np.ndarray, spec):
    from jax.sharding import Mesh, NamedSharding

    mesh = Mesh(np.array(jax.devices()), ("x",))
    return jax.make_array_from_callback(
        values.shape, NamedSharding(mesh, spec), lambda idx: values[idx]
    )


def _assert_local_shards_equal(arr, expected: np.ndarray) -> None:
    for shard in arr.addressable_shards:
        np.testing.assert_array_equal(np.asarray(shard.data), expected[shard.index])


def _install_read_counter():
    from torchsnapshot_tpu.io_types import ReadStream
    from torchsnapshot_tpu.storage_plugins.fs import FSStoragePlugin

    counts: dict = {}

    def add(root, path, n):
        if "replicated/" in path or "sharded/" in path:
            counts[root] = counts.get(root, 0) + n

    orig_read = FSStoragePlugin.read

    async def counting_read(self, read_io, _orig=orig_read):
        await _orig(self, read_io)
        add(self.root, read_io.path, memoryview(read_io.buf).nbytes)

    orig_stream = FSStoragePlugin.read_stream

    async def counting_stream(self, read_io, sub_chunk, _orig=orig_stream):
        inner = await _orig(self, read_io, sub_chunk)
        root = self.root

        async def chunks():
            async for c in inner.chunks:
                add(root, read_io.path, memoryview(c).nbytes)
                yield c

        return ReadStream(path=inner.path, nbytes=inner.nbytes, chunks=chunks())

    FSStoragePlugin.read = counting_read
    FSStoragePlugin.read_stream = counting_stream
    return counts


def _save_rows_worker(rank, world_size, root, port):
    """tp2 row-parallel save; the source rule set rides the metadata."""
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict
    from torchsnapshot_tpu.layout import LayoutSpec, Rule

    arr = _make(jax, _vals(), P("x", None))
    layout = LayoutSpec(
        [("x", world_size)], [Rule.of(r"model/w$", ["x", None])]
    )
    Snapshot.take(root, {"model": StateDict(w=arr)}, layout=layout)
    return "ok"


def _restore_cols_worker(rank, world_size, root, port, mode):
    """Column-parallel restore with TORCHSNAPSHOT_TPU_RESHARD=``mode``;
    cooperation pinned off so the planned tier is measured alone."""
    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = mode
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"  # counters() below
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    telemetry.refresh_from_env()  # the launcher imported us before the env
    counts = _install_read_counter()
    dst = {
        "model": StateDict(
            w=_make(jax, np.zeros((ROWS, COLS), np.float32), P(None, "x"))
        )
    }
    Snapshot(root).restore(dst)
    _assert_local_shards_equal(dst["model"]["w"], _vals())
    c = telemetry.counters()
    return {
        "payload_read": sum(counts.values()),
        "from_peers": int(c.get("bytes_resharded_from_peers", 0)),
        "to_peers": int(c.get("bytes_to_peers", 0)),
        "fallbacks": int(c.get("fanout_fallbacks", 0)),
    }


def test_tp2_to_tp4_planned_reshard_cuts_storage_reads_3x(tmp_path) -> None:
    """The acceptance criterion: the world-4 cross-cut restore reads
    >= 3x fewer payload bytes from storage under the planner than
    direct, bit-exact both ways."""
    root = str(tmp_path / "snap")
    results = run_with_subprocesses(
        _save_rows_worker, 2, root, _find_free_port(), timeout=180.0
    )
    assert all(v == "ok" for v in results.values())

    planned = run_with_subprocesses(
        _restore_cols_worker, 4, root, _find_free_port(), "always",
        timeout=240.0,
    )
    direct = run_with_subprocesses(
        _restore_cols_worker, 4, root, _find_free_port(), "never",
        timeout=240.0,
    )

    payload = _payload()
    planned_read = sum(r["payload_read"] for r in planned.values())
    direct_read = sum(r["payload_read"] for r in direct.values())
    # Direct: every rank reads both row-halves -> 4x the payload.
    assert direct_read >= 3.5 * payload, f"direct read only {direct_read}"
    # Planned: each saved shard is read once fleet-wide (by its owner).
    assert planned_read <= 1.3 * payload, (
        f"planned amplification {planned_read / payload:.2f}x"
    )
    assert direct_read >= 3 * planned_read, (
        f"reduction only {direct_read / max(1, planned_read):.2f}x"
    )
    # The bytes genuinely moved over the peer channel, with no fallback.
    assert sum(r["from_peers"] for r in planned.values()) > 0
    assert sum(r["to_peers"] for r in planned.values()) > 0
    assert all(r["fallbacks"] == 0 for r in planned.values()), planned
    # The direct fleet never touched the planner.
    assert all(r["from_peers"] == 0 for r in direct.values()), direct


def _single_gather_worker(rank, world_size, root, port):
    """Save rows and restore cols in ONE world-2 process: counts every
    ``all_gather_object`` payload during the restore and checks the
    (preverify, addr, coop, reshard, lazy) election tuple rides exactly
    one."""
    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = "always"
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry
    from torchsnapshot_tpu import pg_wrapper as pgw

    telemetry.refresh_from_env()

    arr = _make(jax, _vals(), P("x", None))
    Snapshot.take(root, {"model": StateDict(w=arr)})

    gathered = []
    orig = pgw.PGWrapper.all_gather_object

    def counting(self, obj, *args, _orig=orig, **kwargs):
        gathered.append(obj)
        return _orig(self, obj, *args, **kwargs)

    pgw.PGWrapper.all_gather_object = counting
    try:
        dst = {
            "model": StateDict(
                w=_make(jax, np.zeros((ROWS, COLS), np.float32), P(None, "x"))
            )
        }
        Snapshot(root).restore(dst)
    finally:
        pgw.PGWrapper.all_gather_object = orig
    _assert_local_shards_equal(dst["model"]["w"], _vals())

    election_tuples = [
        o for o in gathered if isinstance(o, tuple) and len(o) == 5
    ]
    from_peers = int(telemetry.counters().get("bytes_resharded_from_peers", 0))
    return {"elections": len(election_tuples), "from_peers": from_peers}


def test_single_election_gather(tmp_path) -> None:
    """Pinned by snapshot.py's ``_group_read_reqs`` docstring: the
    planner's election must ride the ONE existing preverify/coop flag
    all-gather — never a second flag round trip — and the planned path
    must still engage (peer bytes flowed)."""
    results = run_with_subprocesses(
        _single_gather_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        timeout=180.0,
    )
    for rank, r in results.items():
        assert r["elections"] == 1, (rank, results)
    assert sum(r["from_peers"] for r in results.values()) > 0, results


def _skew_worker(rank, world_size, root, port):
    """Env skew: rank 0 votes always, rank 1 never. Unanimity fails;
    the fleet must complete on direct reads — no planned units, no
    hang, bit-exact."""
    os.environ["TORCHSNAPSHOT_TPU_RESHARD"] = "always" if rank == 0 else "never"
    os.environ["TORCHSNAPSHOT_TPU_TELEMETRY"] = "1"
    os.environ["TORCHSNAPSHOT_TPU_COOP_RESTORE"] = "never"
    os.environ["TORCHSNAPSHOT_TPU_COOP_TIMEOUT"] = "30"
    jax = _init_jax_dist(rank, world_size, port)
    from jax.sharding import PartitionSpec as P

    from torchsnapshot_tpu import Snapshot, StateDict, telemetry

    telemetry.refresh_from_env()
    arr = _make(jax, _vals(), P("x", None))
    Snapshot.take(root, {"model": StateDict(w=arr)})
    dst = {
        "model": StateDict(
            w=_make(jax, np.zeros((ROWS, COLS), np.float32), P(None, "x"))
        )
    }
    Snapshot(root).restore(dst)
    _assert_local_shards_equal(dst["model"]["w"], _vals())
    c = telemetry.counters()
    return {
        "from_peers": int(c.get("bytes_resharded_from_peers", 0)),
        "to_peers": int(c.get("bytes_to_peers", 0)),
    }


def test_env_skew_degrades_to_direct_bit_exact(tmp_path) -> None:
    results = run_with_subprocesses(
        _skew_worker, 2, str(tmp_path / "snap"), _find_free_port(),
        timeout=180.0,
    )
    for rank, r in results.items():
        assert r["from_peers"] == 0, (rank, results)
        assert r["to_peers"] == 0, (rank, results)
