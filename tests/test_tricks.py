"""Adapter tests (reference analogue: the DeepSpeed trick's round-trip,
tests exercised via tricks/deepspeed.py)."""

import numpy as np
import pytest

from torchsnapshot_tpu import Snapshot
from torchsnapshot_tpu.tricks import FlaxTrainStateAdapter, PytreeAdapter


def _make_train_state(seed: int):
    import jax
    import jax.numpy as jnp
    import optax
    from flax import linen as nn
    from flax.training import train_state

    model = nn.Dense(4)
    params = model.init(jax.random.PRNGKey(seed), jnp.ones((1, 3)))
    return train_state.TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-3)
    )


def test_flax_train_state_roundtrip(tmp_path):
    import jax
    import jax.numpy as jnp

    state = _make_train_state(0)
    # advance so step/opt moments are non-trivial
    grads = jax.tree.map(jnp.ones_like, state.params)
    state = state.apply_gradients(grads=grads)

    adapter = FlaxTrainStateAdapter(state)
    Snapshot.take(str(tmp_path / "snap"), {"train": adapter})

    dst = FlaxTrainStateAdapter(_make_train_state(1))
    Snapshot(str(tmp_path / "snap")).restore({"train": dst})

    assert int(dst.state.step) == 1
    for a, b in zip(jax.tree.leaves(dst.state.params), jax.tree.leaves(state.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restored state still steps
    dst.state.apply_gradients(grads=grads)


def test_pytree_adapter_roundtrip(tmp_path):
    import jax.numpy as jnp

    tree = {"a": [jnp.arange(4.0), (jnp.ones(2), 3)], "b": {"c": jnp.zeros((2, 2))}}
    Snapshot.take(str(tmp_path / "snap"), {"t": PytreeAdapter(tree)})

    dst = PytreeAdapter(
        {"a": [jnp.zeros(4), (jnp.zeros(2), 0)], "b": {"c": jnp.ones((2, 2))}}
    )
    Snapshot(str(tmp_path / "snap")).restore({"t": dst})
    np.testing.assert_array_equal(np.asarray(dst.tree["a"][0]), np.arange(4.0))
    assert dst.tree["a"][1][1] == 3
    np.testing.assert_array_equal(np.asarray(dst.tree["b"]["c"]), np.zeros((2, 2)))


def test_pytree_adapter_structure_mismatch(tmp_path):
    import jax.numpy as jnp

    Snapshot.take(str(tmp_path / "snap"), {"t": PytreeAdapter({"x": jnp.ones(3)})})
    dst = PytreeAdapter({"y": jnp.ones(3)})
    with pytest.raises(Exception):
        Snapshot(str(tmp_path / "snap")).restore({"t": dst})


def test_orbax_migration(tmp_path):
    ocp = pytest.importorskip("orbax.checkpoint")
    del ocp
    import jax.numpy as jnp

    from torchsnapshot_tpu.tricks.orbax_interop import (
        load_orbax_pytree,
        migrate_from_orbax,
        migrate_to_orbax,
    )

    tree = {"w": np.arange(6, dtype=np.float32).reshape(2, 3), "step": np.int32(5)}
    import orbax.checkpoint as ocp

    with ocp.PyTreeCheckpointer() as ckptr:
        ckptr.save(str(tmp_path / "orbax_src"), tree)

    snap = migrate_from_orbax(
        str(tmp_path / "orbax_src"), str(tmp_path / "snap")
    )
    np.testing.assert_array_equal(snap.read_object("0/app/w"), tree["w"])

    # and back out to orbax
    target = {"w": np.zeros((2, 3), np.float32), "step": np.int32(0)}
    migrate_to_orbax(str(tmp_path / "snap"), str(tmp_path / "orbax_dst"), target)
    out = load_orbax_pytree(str(tmp_path / "orbax_dst"))
    np.testing.assert_array_equal(out["w"], tree["w"])
