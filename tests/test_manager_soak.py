"""CheckpointManager soak: a training job's worth of the manager loop.

The store-GC (`PGWrapper.retire`) and staging-pool recycling claims are
elsewhere tested at ~50-snapshot scale; a real training job runs the
loop for weeks. This soak runs 200+ steps through a REAL 2-process
world — cadence saves, incremental chains, retention pruning, a
mid-run simulated preemption (emergency save), and a mid-run "restart"
(fresh manager resuming from the latest step, re-chaining incrementals)
— and asserts the two resources that would leak first stay FLAT:

- store key count (sampled every save; the retire/GC protocol must
  reclaim every operation's keys), and
- RSS per process (sampled every save; staging buffers must recycle).

Then every retained snapshot is restored and value-checked (state is a
deterministic function of the step), proving retention's base-closure
kept each incremental chain restorable.
"""

from __future__ import annotations

import os

import numpy as np
import pytest

from torchsnapshot_tpu.test_utils import run_with_subprocesses

pytestmark = [pytest.mark.multiprocess, pytest.mark.slow]

STEPS = 220
PREEMPT_AT = 101  # not on the cadence (every 2): only reachable as emergency
RESTART_AT = 150
KEEP_LAST = 3
KEEP_EVERY = 50
SHAPE = (64, 32)


def _state_for(step: int, rank: int):
    import jax.numpy as jnp

    base = np.arange(64 * 32, dtype=np.float32).reshape(SHAPE)
    return {
        "train": {
            # Rank mixed into the VALUE: restore verification would miss
            # a payload routed to the wrong rank if both ranks held
            # identical bytes.
            "w": jnp.asarray(base + step + 100_000 * rank),
            "host": base * 2 + step,  # replicated host state
            "step": step,
        }
    }


def _soak_worker(rank, world_size, root):
    import resource
    import signal

    import jax

    jax.config.update("jax_platforms", "cpu")

    from torchsnapshot_tpu import CheckpointManager, PreemptionWatcher, StateDict
    from torchsnapshot_tpu.pg_wrapper import get_default_pg

    pg = get_default_pg()
    store = pg.store

    def mgr_kwargs():
        return dict(
            save_interval_steps=2,
            keep_last=KEEP_LAST,
            keep_every=KEEP_EVERY,
            async_save=True,
            incremental=True,
            replicated=["train/host"],
            pg=pg,
        )

    watcher = PreemptionWatcher(signals=(signal.SIGUSR1,))
    mgr = CheckpointManager(root, preemption=watcher, **mgr_kwargs())

    keys = []
    rss = []
    saved_steps = []
    for step in range(STEPS):
        if step == PREEMPT_AT and rank == 1:
            # Preemption hits ONE rank; the collective decision must make
            # every rank emergency-save this step.
            os.kill(os.getpid(), signal.SIGUSR1)
        if step == RESTART_AT:
            # Mid-run restart: drain, then a FRESH manager resumes from
            # the latest committed step and re-chains incrementals on it.
            mgr.wait()
            mgr = CheckpointManager(root, **mgr_kwargs())
            resumed = mgr.latest_step()
            assert resumed is not None and resumed >= RESTART_AT - 2
        app = {"train": StateDict(**_state_for(step, rank)["train"])}
        if mgr.save(step, app):
            saved_steps.append(step)
        keys.append(store.num_keys())
        rss.append(
            resource.getrusage(resource.RUSAGE_SELF).ru_maxrss  # KB on Linux
        )
    mgr.wait()
    watcher.close()

    # ---- flat-curve assertions (per process) -------------------------
    # Store keys: bounded and non-growing. Compare a late-run window
    # against an early one (post-warmup): any per-operation key leak
    # over ~90 saves would separate the medians.
    early = sorted(keys[20:40])[10]
    late = sorted(keys[-20:])[10]
    assert late <= early + 8, f"store keys grew: early~{early} late~{late}"
    # Peak RSS: the high-water mark must stop rising once the loop is
    # warm — a leak of even ~100 KB/save would add >10 MB over the run.
    assert rss[-1] - rss[39] < 64 * 1024, (  # ru_maxrss is in KB
        f"peak RSS kept climbing: step40={rss[39]}KB end={rss[-1]}KB"
    )
    assert PREEMPT_AT in saved_steps, "emergency save did not happen"
    return {
        "saved": saved_steps,
        "early_keys": early,
        "late_keys": late,
        "rss_mb": rss[-1] // 1024,
    }


def _verify_worker(rank, world_size, root):
    """Every retained snapshot restores and value-checks (the incremental
    chains' base closure held through ~100 retention passes)."""
    import jax

    jax.config.update("jax_platforms", "cpu")

    from torchsnapshot_tpu import CheckpointManager, StateDict

    mgr = CheckpointManager(root, keep_last=KEEP_LAST, keep_every=KEEP_EVERY)
    steps = mgr.all_steps()
    for step in steps:
        dst = {
            "train": StateDict(
                **{
                    k: (v * 0 if hasattr(v, "shape") else -1)
                    for k, v in _state_for(step, rank)["train"].items()
                }
            )
        }
        mgr.restore(dst, step=step)
        want = _state_for(step, rank)["train"]
        assert dst["train"]["step"] == step
        np.testing.assert_array_equal(
            np.asarray(dst["train"]["w"]), np.asarray(want["w"])
        )
        np.testing.assert_array_equal(dst["train"]["host"], want["host"])
    return steps


def test_manager_soak_200_steps(tmp_path) -> None:
    root = str(tmp_path / "ckpts")
    results = run_with_subprocesses(_soak_worker, 2, root, timeout=900.0)
    assert set(results) == {0, 1}
    # Both ranks made the same save decisions (collective consistency),
    # including the off-cadence emergency step.
    assert results[0]["saved"] == results[1]["saved"]

    # Retention: newest KEEP_LAST saves + keep_every multiples survive
    # (+ any incremental bases they need, which value-verification below
    # exercises implicitly).
    results_v = run_with_subprocesses(_verify_worker, 2, root, timeout=600.0)
    steps = results_v[0]
    assert results_v[1] == steps
    saved = results[0]["saved"]
    expected_keep = set(saved[-KEEP_LAST:]) | {
        s for s in saved if s % KEEP_EVERY == 0
    }
    assert expected_keep <= set(steps), (expected_keep, steps)
    # Pruning actually happened: far fewer snapshots than saves.
    assert len(steps) < len(saved) // 3, (len(steps), len(saved))
