# Sphinx configuration for torchsnapshot_tpu.
#
# Mirrors the scope of the reference docs tree (reference: docs/source/conf.py)
# with autodoc pulling API reference from the package docstrings.

import os
import sys

sys.path.insert(0, os.path.abspath("../.."))

project = "torchsnapshot_tpu"
copyright = "2026, torchsnapshot_tpu authors"
author = "torchsnapshot_tpu authors"

from torchsnapshot_tpu.version import __version__  # noqa: E402

version = __version__
release = __version__

extensions = [
    "sphinx.ext.autodoc",
    "sphinx.ext.autosummary",
    "sphinx.ext.intersphinx",
    "sphinx.ext.napoleon",
    "sphinx.ext.viewcode",
]

autodoc_member_order = "bysource"
autodoc_typehints = "description"
autosummary_generate = True

intersphinx_mapping = {
    "python": ("https://docs.python.org/3", None),
    "jax": ("https://docs.jax.dev/en/latest/", None),
    "numpy": ("https://numpy.org/doc/stable/", None),
}

templates_path = ["_templates"]
exclude_patterns = []

html_theme = "alabaster"
html_static_path = []
